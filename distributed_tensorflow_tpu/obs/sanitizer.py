"""locktrace: opt-in runtime lock-order sanitizer.

``sanitize_locks()`` monkeypatches ``threading.Lock`` and
``threading.Condition`` so every lock created inside the context is a
``TrackedLock`` that records, per acquisition, which locks the acquiring
thread already held. Those (held → acquired) edges form a directed
acquisition-order graph; a cycle in it means two threads can take the same
locks in opposite orders — a potential deadlock, reported even if the
interleaving never actually deadlocked during the test run.

Nodes are *creation sites* (``file:lineno`` of the ``Lock()`` call), not
instances, so the pattern generalizes across pool/queue instances created
from the same line. Self-edges (site → same site) are ignored: nested
acquisition of two instances from one constructor line (e.g. two queues)
is ordered by the caller, not by this graph.

Only locks constructed *while the patch is installed* are tracked —
pre-existing module locks and stdlib internals (logging, importlib) keep
their native types, so the sanitizer cannot perturb code outside the
system under test. ``queue.Queue`` and ``threading.Event`` objects built
inside the window *are* tracked (their internal mutex/Condition route
through the patched constructors), which is exactly what the batcher /
prefetch soak tests want.
"""

from __future__ import annotations

import threading
import traceback
from contextlib import contextmanager

__all__ = ["LockOrderSanitizer", "sanitize_locks"]

_REAL_LOCK = threading.Lock
_REAL_CONDITION = threading.Condition


def _creation_site(skip_prefixes: tuple[str, ...]) -> str:
    """file:lineno of the frame that called Lock()/Condition()."""
    for frame in reversed(traceback.extract_stack(limit=12)[:-2]):
        fname = frame.filename
        if any(p in fname for p in skip_prefixes):
            continue
        return f"{fname.rsplit('/', 1)[-1]}:{frame.lineno}"
    return "<unknown>"


class LockOrderSanitizer:
    """Acquisition graph + cycle detection over tracked locks."""

    def __init__(self) -> None:
        self._graph_lock = _REAL_LOCK()
        # site -> set of sites acquired while holding it, with one example
        # stack edge label for the report.
        self._edges: dict[str, set[str]] = {}
        self._held = threading.local()
        self.acquisitions = 0

    # -- called by TrackedLock ------------------------------------------

    def _stack(self) -> list[str]:
        if not hasattr(self._held, "stack"):
            self._held.stack = []
        return self._held.stack

    def note_acquired(self, site: str) -> None:
        stack = self._stack()
        if stack:
            holder = stack[-1]
            if holder != site:
                with self._graph_lock:
                    self._edges.setdefault(holder, set()).add(site)
        with self._graph_lock:
            self.acquisitions += 1
        stack.append(site)

    def note_released(self, site: str) -> None:
        stack = self._stack()
        # Locks may be released out of LIFO order (Condition.wait releases
        # the underlying lock mid-stack); remove the most recent entry.
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == site:
                del stack[i]
                return

    # -- reporting ------------------------------------------------------

    def edges(self) -> dict[str, set[str]]:
        with self._graph_lock:
            return {k: set(v) for k, v in self._edges.items()}

    def cycles(self) -> list[list[str]]:
        """All elementary acquisition-order cycles (DFS, deduplicated)."""
        graph = self.edges()
        cycles: list[list[str]] = []
        seen: set[tuple[str, ...]] = set()

        def dfs(node: str, path: list[str], on_path: set[str]) -> None:
            for nxt in sorted(graph.get(node, ())):
                if nxt in on_path:
                    cyc = path[path.index(nxt):] + [nxt]
                    # Canonicalize rotation so each cycle reports once.
                    core = cyc[:-1]
                    k = core.index(min(core))
                    canon = tuple(core[k:] + core[:k])
                    if canon not in seen:
                        seen.add(canon)
                        cycles.append(list(canon) + [canon[0]])
                elif nxt not in path:
                    dfs(nxt, path + [nxt], on_path | {nxt})

        for start in sorted(graph):
            dfs(start, [start], {start})
        return cycles

    def report(self) -> str:
        lines = [f"lock-order sanitizer: {self.acquisitions} acquisitions"]
        for src in sorted(self._edges):
            for dst in sorted(self._edges[src]):
                lines.append(f"  {src} -> {dst}")
        cycles = self.cycles()
        if cycles:
            lines.append("POTENTIAL DEADLOCK CYCLES:")
            for cyc in cycles:
                lines.append("  " + " -> ".join(cyc))
        else:
            lines.append("no acquisition-order cycles")
        return "\n".join(lines)

    def assert_no_cycles(self) -> None:
        cycles = self.cycles()
        if cycles:
            raise AssertionError(
                "lock acquisition-order cycle(s) detected:\n" + self.report()
            )


class TrackedLock:
    """Drop-in ``threading.Lock`` recording acquisition order."""

    def __init__(self, sanitizer: LockOrderSanitizer, site: str) -> None:
        self._lock = _REAL_LOCK()
        self._san = sanitizer
        self._site = site

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._san.note_acquired(self._site)
        return got

    def release(self) -> None:
        self._lock.release()
        self._san.note_released(self._site)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<TrackedLock {self._site} {self._lock!r}>"


@contextmanager
def sanitize_locks(
    skip_prefixes: tuple[str, ...] = ("threading.py", "sanitizer.py", "queue.py")
):
    """Context manager: track all locks created inside; yields the sanitizer.

    ``threading.Condition`` keeps its stdlib implementation but, created
    with no argument, now wraps a ``TrackedLock`` — the stdlib Condition
    handles foreign locks via its documented ``acquire(0)``/default
    ``_release_save`` fallbacks, so ``with cv:`` and ``cv.wait()`` record
    acquire/release events like any other tracked lock. Waiter locks are
    ``_thread.allocate_lock`` internals and stay untracked.
    """
    san = LockOrderSanitizer()

    def make_lock() -> TrackedLock:
        return TrackedLock(san, _creation_site(skip_prefixes))

    def make_condition(lock=None):
        if lock is None:
            lock = make_lock()
        return _REAL_CONDITION(lock)

    threading.Lock = make_lock  # type: ignore[assignment]
    threading.Condition = make_condition  # type: ignore[assignment]
    try:
        yield san
    finally:
        threading.Lock = _REAL_LOCK  # type: ignore[assignment]
        threading.Condition = _REAL_CONDITION  # type: ignore[assignment]
