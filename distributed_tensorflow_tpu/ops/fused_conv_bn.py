r"""Fused 1x1-conv + BatchNorm (+ReLU) with a fully-fused Pallas backward.

The r4 kernel family docs/PERF.md:97-110 calls for — the one remaining path
toward the measured ~0.30 MFU ceiling for ResNet-50 on this chip (VERDICT r3
Missing #1). The r3 campaign proved per-op conv efficiency was never the
binding constraint: three Pallas dgrad strategies each beat XLA 3-5x per-op
and each LOST at the step level, because XLA fuses the ReLU mask and the two
BatchNorm-backward per-channel reductions into its dgrad convs, and an
opaque kernel evicted those riders into standalone passes. This module
absorbs them: the backward takes the extra operands (the saved conv output,
the per-channel BN stats) and emits the two reductions as extra outputs, so
NOTHING falls out of the fusion when the Pallas op replaces it.

Forward (XLA-land on purpose — its fused producer chains already saturate
bandwidth, docs/PERF.md r3):

    z  = x @ W                  (1x1 conv as matmul, bf16, MXU)
    mu, var = batch stats(z)    (f32, fast-variance form like flax BN)
    a  = relu?(gamma * (z - mu) * rsqrt(var+eps) + beta)

Backward (two Pallas kernels, one logical pass-pair over [M, N]):

    g   = dA * mask             mask = (gamma*x_hat+beta > 0) recomputed
    s1  = sum_m g               \  reduce kernel: streaming read of dA, z;
    s2  = sum_m g * x_hat       /  per-tile partial rows, summed in XLA
    dz  = gamma*inv * (g - s1/M - x_hat*s2/M)      per-element, in-register
    dx  = dz @ W^T              \  apply kernel: dz recomputed per tile
    dW  = x^T @ dz              /  feeds BOTH matmuls — never materialized
    dgamma = s2, dbeta = s1     (free riders of the reduce kernel)

HBM traffic: 4 reads of [M,N] + 1 read/1 write of [M,K] vs the unfused
XLA chain's ~7 [M,N] passes + the same [M,K] traffic — and unlike the r3
kernels, the epilogue work XLA fuses into its dgrads (ReLU mask, BN-bwd
sums) is absorbed by the kernels. Layouts follow the r3 measurement:
activations with C >= 128 flatten in H,W,B,C order (a bitcast at the Pallas
boundary); C = 64 tensors would force relayout copies, so those shapes are
gated off to the plain path (see :func:`fused_supported`). Strided (proj)
units DO fuse: their python-slice stride lowers to gather/scatter-add
pairs around the custom-vjp boundary, but gating them off measured WORSE
in-step (53.5 vs 50.9 ms at b=128) — the proj matmul win exceeds the
slice tax (docs/PERF.md r4).

The running-stat bookkeeping (flax ``batch_stats`` collection) lives in
models/resnet.py's ``_BNParamsStats``/``_Conv1x1Kernel`` holder modules
(param trees bit-compatible with nn.Conv + nn.BatchNorm); this file is
pure function + VJP.

Reference parity: replaces the reference's cuDNN conv + fused-BN training
blocks inside its ResNet-50/Inception workloads (SURVEY.md §2 rows); math is
identical to ``nn.Conv(f,(1,1))`` + ``nn.BatchNorm`` + relu up to f32
reduction order (pinned by tests/test_fused_conv_bn.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_MAX_KN = 4096
# Per-tile VMEM budget (bytes) for the apply kernel's streamed operands —
# double-buffered pipelines must leave room for W [K,N] and the dW [K,N] f32
# accumulator, which stay resident.
_TILE_BYTES = 2 * 1024 * 1024


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def _tile_m(m: int, k: int, n: int) -> int | None:
    """Largest multiple-of-16 divisor of m whose tile working set fits."""
    # Streamed per tile: dA, z [TM, N] bf16 + x, dx [TM, K] bf16.
    cap = max(16, _TILE_BYTES // max(1, 2 * (2 * n + 2 * k)))
    for t in range(min(1024, cap, m) & ~15, 15, -16):
        if m % t == 0:
            return t
    return None


def fused_supported(m: int, k: int, n: int) -> bool:
    """Shapes the fused backward handles with bitcast boundaries.

    Both channel dims must be >= 128 (C = 64 activations live in XLA's
    B-minor layout; the flatten would materialize a relayout — the measured
    step-level loss of the r3 generic kernels) and the M dim must tile.
    ``FUSED_CONV_BN_MAXM`` / ``FUSED_CONV_BN_MINM`` (env) bound the M range
    that fuses — the per-stage bisection/tuning knob (M is stage-unique in
    ResNet-50: 401408 / 100352 / 25088 / 6272 at b=128).
    """
    import os

    maxm = int(os.environ.get("FUSED_CONV_BN_MAXM", "0") or 0)
    minm = int(os.environ.get("FUSED_CONV_BN_MINM", "0") or 0)
    if (maxm and m > maxm) or (minm and m < minm):
        return False
    return (
        128 <= k <= _MAX_KN
        and 128 <= n <= _MAX_KN
        and _tile_m(m, k, n) is not None
    )


# ---------------------------------------------------------------------------
# Kernels. Per-channel constants ride as one [8, N] f32 ref:
#   row 0: mu, 1: inv (rsqrt(var+eps)), 2: gamma, 3: beta,
#   row 4: s1/M, 5: s2/M (apply kernel only; zero for the reduce kernel).
# ---------------------------------------------------------------------------


def _g_xhat(da_ref, z_ref, c_ref, relu: bool):
    da = da_ref[:].astype(jnp.float32)
    xh = (z_ref[:].astype(jnp.float32) - c_ref[0, :]) * c_ref[1, :]
    if relu:
        mask = (c_ref[2, :] * xh + c_ref[3, :]) > 0.0
        g = jnp.where(mask, da, 0.0)
    else:
        g = da
    return g, xh


def _reduce_kernel(da_ref, z_ref, c_ref, s_ref, *, relu):
    # Partial sums land in this tile's OWN row pair s[i] = [s1_i; s2_i]
    # (pure streaming, no read-modify-write of a shared accumulator — the
    # v1 serialized [1, N] output measured ~4x off roofline); the [tiles,
    # 2, N] partials reduce in XLA, which is tiny.
    g, xh = _g_xhat(da_ref, z_ref, c_ref, relu)
    s_ref[0, 0, :] = jnp.sum(g, axis=0)
    s_ref[0, 1, :] = jnp.sum(g * xh, axis=0)


def _apply_kernel(da_ref, z_ref, x_ref, w_ref, c_ref, dx_ref, dw_ref, *, relu):
    g, xh = _g_xhat(da_ref, z_ref, c_ref, relu)
    dz = (c_ref[2, :] * c_ref[1, :]) * (g - c_ref[4, :] - xh * c_ref[5, :])
    dz_lo = dz.astype(w_ref.dtype)
    # dx[TM, K] = dz[TM, N] @ W[K, N]^T — contract N, no explicit transpose.
    dx_ref[:] = lax.dot_general(
        dz_lo,
        w_ref[:],
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(dx_ref.dtype)
    # dW[K, N] += x[TM, K]^T @ dz[TM, N] — sequential-grid accumulation.
    part = lax.dot_general(
        x_ref[:],
        dz_lo,
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(pl.program_id(0) == 0)
    def _init():
        dw_ref[:] = part

    @pl.when(pl.program_id(0) != 0)
    def _acc():
        dw_ref[:] = dw_ref[:] + part


def _pack_consts(mu, inv, gamma, beta, c1=None, c2=None):
    n = mu.shape[0]
    z = jnp.zeros((n,), jnp.float32)
    rows = [mu, inv, gamma, beta, c1 if c1 is not None else z,
            c2 if c2 is not None else z, z, z]
    return jnp.stack([r.astype(jnp.float32) for r in rows])


def _bn_bwd_reduce(da2, z2, consts, relu: bool, interpret: bool):
    m, n = da2.shape
    tm = _tile_m(m, 0, n) or m
    tiles = m // tm
    s = pl.pallas_call(
        functools.partial(_reduce_kernel, relu=relu),
        grid=(tiles,),
        in_specs=[
            pl.BlockSpec((tm, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((8, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, 2, n), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((tiles, 2, n), jnp.float32),
        interpret=interpret,
    )(da2, z2, consts)
    total = jnp.sum(s, axis=0)
    return total[0], total[1]


def _bn_bwd_apply(da2, z2, x2, w2, consts, relu: bool, interpret: bool):
    m, n = da2.shape
    k = x2.shape[1]
    tm = _tile_m(m, k, n)
    dx, dw = pl.pallas_call(
        functools.partial(_apply_kernel, relu=relu),
        grid=(m // tm,),
        in_specs=[
            pl.BlockSpec((tm, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, k), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((8, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tm, k), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, k), da2.dtype),
            jax.ShapeDtypeStruct((k, n), jnp.float32),
        ],
        interpret=interpret,
    )(da2, z2, x2, w2, consts)
    return dx, dw


# ---------------------------------------------------------------------------
# The custom-VJP unit over flattened [M, C] views.
# ---------------------------------------------------------------------------


def _fwd_math(x2, w2, gamma, beta, relu: bool, eps: float):
    z2 = jnp.dot(x2, w2)
    zf = z2.astype(jnp.float32)
    m = zf.shape[0]
    mean = jnp.mean(zf, axis=0)
    # Fast-variance form, matching flax BatchNorm's default.
    var = jnp.mean(jnp.square(zf), axis=0) - jnp.square(mean)
    inv = lax.rsqrt(var + eps)
    y = (zf - mean) * (inv * gamma) + beta
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(x2.dtype), z2, mean, var, inv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _fused(x2, w2, gamma, beta, relu, eps, interpret):
    a2, _, mean, var, _ = _fwd_math(x2, w2, gamma, beta, relu, eps)
    return a2, mean, var


def _fused_fwd(x2, w2, gamma, beta, relu, eps, interpret):
    a2, z2, mean, var, inv = _fwd_math(x2, w2, gamma, beta, relu, eps)
    return (a2, mean, var), (x2, w2, z2, mean, inv, gamma, beta)


def _fused_bwd(relu, eps, interpret, res, cts):
    # The mean/var outputs exist for running-stat bookkeeping only — their
    # cotangents are dropped (stop-gradient semantics, same as flax's
    # running averages; the batch-stat gradient paths through the
    # NORMALIZATION are the s1/s2 terms below, which are exact).
    da2, _, _ = cts
    x2, w2, z2, mean, inv, gamma, beta = res
    m = x2.shape[0]
    # Reduce-kernel history (all measured in-step, b=128, stages 3-4):
    # v1 grid-serialized [1, N] accumulator — ~4x off roofline; v2 plain
    # XLA reductions — WORSE (the pass didn't fuse with da2's producer
    # across the custom-vjp boundary and re-materialized g); v3 (current)
    # per-tile partial rows, pure streaming, summed in XLA.
    consts = _pack_consts(mean, inv, gamma, beta)
    s1, s2 = _bn_bwd_reduce(da2, z2, consts, relu, interpret)
    consts = _pack_consts(mean, inv, gamma, beta, s1 / m, s2 / m)
    dx2, dw = _bn_bwd_apply(da2, z2, x2, w2, consts, relu, interpret)
    return dx2, dw.astype(w2.dtype), s2, s1


_fused.defvjp(_fused_fwd, _fused_bwd)


def conv1x1_bn_act(
    x4: jax.Array,
    kernel: jax.Array,
    gamma: jax.Array,
    beta: jax.Array,
    *,
    relu: bool,
    strides: int = 1,
    eps: float = 1e-5,
    interpret: bool | None = None,
):
    """1x1 conv + train-mode BatchNorm (+ReLU) with the fused Pallas backward.

    Args:
      x4: ``[B, H, W, K]`` activations (bf16 recommended).
      kernel: ``[1, 1, K, N]`` or ``[K, N]`` conv kernel (cast to x4.dtype).
      gamma, beta: BN scale/bias ``[N]`` (f32).
      relu: apply ReLU after the BN (Conv_0/Conv_1 positions; the block's
        final BN feeds the residual add, whose ReLU lives outside).
      strides: spatial stride (a strided 1x1 conv = slice then matmul).

    Returns:
      ``(a [B, H', W', N], batch_mean [N], batch_var [N])`` — activations
      plus the batch statistics for the caller's running-average update
      (their gradient is stopped; see ``_fused_bwd``).

    Shapes must pass :func:`fused_supported`; callers gate on it.
    """
    if interpret is None:
        interpret = not _on_tpu()
    if kernel.ndim == 4:
        kernel = kernel[0, 0]
    if strides > 1:
        x4 = x4[:, ::strides, ::strides, :]
    b, h, w, k = x4.shape
    n = kernel.shape[1]
    if not fused_supported(h * w * b, k, n):
        # Fail loudly here instead of an opaque TypeError from _tile_m()
        # being None deep inside the backward grid computation.
        raise ValueError(
            f"conv1x1_bn_act: shape (M={h * w * b}, K={k}, N={n}) is outside "
            "the fused kernel family's supported range; gate callers on "
            "fused_supported(m, k, n)"
        )
    # H,W,B,C flatten: a bitcast for XLA:TPU's {3,0,2,1} conv layouts at
    # C >= 128 (docs/PERF.md r3 — B,H,W,C order costs a materialized
    # relayout copy per boundary).
    x2 = x4.transpose(1, 2, 0, 3).reshape(h * w * b, k)
    a2, mean, var = _fused(
        x2, kernel.astype(x4.dtype), gamma, beta, relu, eps, interpret
    )
    a4 = a2.reshape(h, w, b, n).transpose(2, 0, 1, 3)
    return a4, mean, var


# ---------------------------------------------------------------------------
# Flax-side plumbing shared by every model that hosts a fused unit
# (models/resnet.py BottleneckBlock, models/inception.py BasicConv). The
# holder modules declare EXACTLY the leaves nn.Conv(use_bias=False) and
# nn.BatchNorm would, under the same child names, so param trees and
# checkpoints interchange across backends.
# ---------------------------------------------------------------------------


from collections.abc import Callable

from flax import linen as nn


class Conv1x1Kernel(nn.Module):
    """Kernel-param holder — declares exactly the ``kernel`` leaf
    ``nn.Conv(features, (1,1), use_bias=False)`` would."""

    cin: int
    features: int

    @nn.compact
    def __call__(self):
        return self.param(
            "kernel",
            nn.initializers.he_normal(),
            (1, 1, self.cin, self.features),
            jnp.float32,
        )


class BNParamsStats(nn.Module):
    """BatchNorm param/stat holder matching ``nn.BatchNorm``'s tree. First
    call (no args) reads scale/bias; second call folds the fused op's batch
    stats into the running averages (flax momentum rule)."""

    features: int
    momentum: float = 0.9
    scale_init: Callable = nn.initializers.ones_init()

    @nn.compact
    def __call__(self, batch_mean=None, batch_var=None):
        f = self.features
        scale = self.param("scale", self.scale_init, (f,), jnp.float32)
        bias = self.param(
            "bias", nn.initializers.zeros_init(), (f,), jnp.float32
        )
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((f,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((f,), jnp.float32)
        )
        if batch_mean is not None and not self.is_initializing():
            m = self.momentum
            ra_mean.value = m * ra_mean.value + (1 - m) * batch_mean
            ra_var.value = m * ra_var.value + (1 - m) * batch_var
        return scale, bias


def fused_unit(
    x,
    features: int,
    *,
    relu: bool,
    conv_name: str,
    bn_name: str,
    dtype,
    strides: int = 1,
    eps: float = 1e-5,
    scale_init=None,
):
    """One conv1x1+BN(+ReLU) fused unit, declared under the CALLER's scope.

    Must be called from inside a flax ``@nn.compact`` ``__call__`` — the
    holder modules (kernel param under ``conv_name``, BN params/stats under
    ``bn_name``) attach to the calling module. Shared by ResNet's
    BottleneckBlock and Inception's BasicConv so fused-unit fixes land
    once.
    """
    kernel = Conv1x1Kernel(x.shape[-1], features, name=conv_name)()
    bn = BNParamsStats(
        features,
        scale_init=scale_init or nn.initializers.ones_init(),
        name=bn_name,
    )
    scale, bias = bn()
    a, bm, bv = conv1x1_bn_act(
        x.astype(dtype), kernel, scale, bias,
        relu=relu, strides=strides, eps=eps,
    )
    bn(bm, bv)  # flax momentum-rule running-average update
    return a
