"""1x1 convolution with Pallas backward kernels — the ResNet-50 hot path.

Why this exists (r3 perf frontier, VERDICT r2 Missing #1): the
scripts/hlo_breakdown.py trace of the b=128 ResNet-50 step shows XLA:TPU's
*backward* machinery for 1x1 convolutions running at 8–25 TF/s and
~80–160 GB/s — 4–5x below this chip's measured ~650 GB/s streaming bandwidth
(scripts/roofline.py), 16.7 ms of dgrad + 11.2 ms of wgrad in a 46.4 ms step.
The r2 attempt to express these as ``jnp.dot`` failed because XLA
canonicalizes spatial-reshape dots back into convolution HLO (docs/PERF.md
"dead ends").  A ``jax.custom_vjp`` whose backward calls Pallas kernels is
opaque to that canonicalization: the dgrad and wgrad become plain tiled
matmuls on the MXU with streaming-bound traffic.

The forward stays ``jnp.dot`` on purpose: the trace shows XLA's fused
BN+ReLU→1x1-conv forward already saturates bandwidth (~650 GB/s), and keeping
it in XLA-land lets the preceding BatchNorm/ReLU keep fusing into the conv's
input read — a Pallas forward would force that producer chain to materialize.

Math (x2: [M, K] = flattened [H*W*B, Cin], w: [K, N]):
    fwd:    y  = x2 @ w                      (XLA)
    dgrad:  dx = g @ w^T     — Pallas when K >= 128, else XLA
    wgrad:  dw = x2^T @ g    — XLA (jnp.dot; canonicalized to conv-wgrad)

Selectivity is measured, not guessed (standalone kernel duels vs the
in-step XLA times from the same trace, b=128):

    shape (M, K, N)        XLA dgrad   Pallas dgrad     XLA wgrad  Pallas
    401408, 256,  64        1.2-1.5 ms   0.32 ms (810GB/s)  0.34    0.44
    401408,  64, 256        0.6-0.7      0.96 (263GB/s!)    0.55    0.93
    100352, 512, 128        0.5-0.7      0.16 (825)         0.21    0.15
    100352, 128, 512        0.35         0.13 (1021)        0.17    0.23
     25088,1024, 256        ~0.3         0.10 (665)         —       0.12

Pallas dgrad wins 3-5x whenever the output's minor dim K >= 128; at K=64
Mosaic's half-empty lanes lose to XLA, so those convs keep the XLA path.
Pallas wgrad never beats XLA's in-step fused wgrad convincingly, so the
custom bwd computes dw as a plain dot and lets XLA canonicalize it into
exactly the conv-wgrad it runs today.

Reference parity: this replaces the reference's cuDNN-backed 1x1 conv
layers inside its ResNet-50 allreduce workload (SURVEY.md §2 "ResNet-50 /
ImageNet workload" row); semantics are bit-identical to
``nn.Conv(features, (1,1))`` up to f32-accumulation rounding.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Block working sets stay < ~4 MB each so double-buffered pipelines fit VMEM
# comfortably (v5e); 1024 caps the M tile, K/N are never tiled (<= 2048 for
# every 1x1 in ResNet-50/Inception).
_MAX_TILE_M = 1024
_MAX_KN = 4096


def _tile_m(m: int) -> int | None:
    """Largest multiple-of-16 divisor of m, capped at _MAX_TILE_M."""
    for t in range(min(_MAX_TILE_M, m), 15, -16):
        if t % 16 == 0 and m % t == 0:
            return t
    return None


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def _dgrad_kernel(g_ref, w_ref, o_ref):
    # dx[TM, K] = g[TM, N] @ w[K, N]^T, contracted on N without an explicit
    # transpose (Mosaic handles the transposed operand internally).
    o_ref[:] = jax.lax.dot_general(
        g_ref[:],
        w_ref[:],
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(o_ref.dtype)


def _wgrad_kernel(x_ref, g_ref, o_ref):
    part = jax.lax.dot_general(
        x_ref[:],
        g_ref[:],
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(pl.program_id(0) == 0)
    def _init():
        o_ref[:] = part

    @pl.when(pl.program_id(0) != 0)
    def _acc():
        o_ref[:] = o_ref[:] + part


def _dgrad_pallas(g, w, *, interpret: bool):
    m, n = g.shape
    k = w.shape[0]
    tm = _tile_m(m)
    return pl.pallas_call(
        _dgrad_kernel,
        grid=(m // tm,),
        in_specs=[
            pl.BlockSpec((tm, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tm, k), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, k), g.dtype),
        interpret=interpret,
    )(g, w)


def _wgrad_pallas(x2, g, *, interpret: bool):
    m, k = x2.shape
    n = g.shape[1]
    tm = _tile_m(m)
    return pl.pallas_call(
        _wgrad_kernel,
        grid=(m // tm,),
        in_specs=[
            pl.BlockSpec((tm, k), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((k, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((k, n), jnp.float32),
        interpret=interpret,
    )(x2, g)


def _supported(m: int, k: int, n: int) -> bool:
    # Both channel dims >= 128: (a) K = 64 dgrad output leaves half of every
    # 128-lane register empty and measures slower than XLA; (b) any C = 64
    # activation gets XLA's B-minor layout {0,3,2,1}, so the H,W,B,C flatten
    # at the Pallas boundary materializes a relayout copy instead of a
    # bitcast — the copy tax exceeds the kernel win (measured step-level).
    return (
        _tile_m(m) is not None and 128 <= k <= _MAX_KN and 128 <= n <= _MAX_KN
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _pw_matmul(x2, w, interpret):
    return jnp.dot(x2, w)


def _pw_fwd(x2, w, interpret):
    return jnp.dot(x2, w), (x2, w)


def _pw_bwd(interpret, res, g):
    x2, w = res
    dx = _dgrad_pallas(g, w, interpret=interpret)
    # wgrad deliberately stays in XLA-land: the plain dot is canonicalized
    # into the same fused conv-wgrad XLA runs for nn.Conv, which beats the
    # Pallas split-K kernel at these shapes (module docstring table).
    dw = jax.lax.dot_general(
        x2, g, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    return dx.astype(x2.dtype), dw.astype(w.dtype)


_pw_matmul.defvjp(_pw_fwd, _pw_bwd)


def pointwise_matmul(x2: jax.Array, w: jax.Array) -> jax.Array:
    """``x2 @ w`` with Pallas dgrad/wgrad when shapes allow, else plain dot.

    x2: [M, K]; w: [K, N].  Off-TPU the Pallas kernels run in interpreter
    mode so CPU tests exercise the identical code path.
    """
    m, k = x2.shape
    n = w.shape[1]
    if not _supported(m, k, n):
        return jnp.dot(x2, w)
    return _pw_matmul(x2, w, not _on_tpu())


# ---------------------------------------------------------------------------
# Layout-native dgrad for N=64 outputs (stage-1 Conv_0: the worst op class)
# ---------------------------------------------------------------------------
#
# A 64-channel activation gets XLA:TPU layout {0,3,2,1} — physically
# (H, W, C, B) with B in the lanes — so the generic [M, C] flattening
# materializes a relayout at the Pallas boundary. This path instead bitcasts
# the cotangent to its native [H*W, C, B] view and contracts C in-kernel
# (Mosaic handles the sublane contraction), emitting dx in the [H*W, B, K]
# view that bitcasts straight into the consumer's {3,0,2,1} layout.
# Standalone: 0.28-0.31 ms at b=128 stage-1 geometry vs XLA's 1.24-1.51 ms
# (840-922 GB/s vs ~150). In-step it STILL nets negative (51.9 vs 48.4
# ms/step with only this path enabled) — the BN-backward reductions and
# relu masks that ride XLA's dgrad fusions cost more as standalone passes
# than the kernel saves. Third integration strategy, same verdict: only a
# kernel that absorbs the fused epilogue work can win (docs/PERF.md r3).


def _dgrad_n64_kernel(g_ref, wt_ref, o_ref):
    # g: [thw, C, B]; wt: [C, K]; o: [thw, B, K] — contraction over C.
    o_ref[:] = jax.lax.dot_general(
        g_ref[:],
        wt_ref[:],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(o_ref.dtype)


def _dgrad_n64(g4, w, *, interpret: bool):
    """dx4 [B,H,W,K] from g4 [B,H,W,64] via the native-layout views."""
    b, h, w_, n = g4.shape
    k = w.shape[0]
    hw = h * w_
    thw = next((t for t in (112, 56, 16, 8, 4, 2, 1) if hw % t == 0))
    gv = g4.transpose(1, 2, 3, 0).reshape(hw, n, b)
    dxv = pl.pallas_call(
        _dgrad_n64_kernel,
        grid=(hw // thw,),
        in_specs=[
            pl.BlockSpec((thw, n, b), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((n, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((thw, b, k), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((hw, b, k), g4.dtype),
        interpret=interpret,
    )(gv, jnp.swapaxes(w, 0, 1))  # w [K, N] -> wt [N, K]
    return dxv.reshape(h, w_, b, k).transpose(2, 0, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _pw4d_n64(x4, w, interpret):
    b, h, w_, k = x4.shape
    return jnp.dot(x4.reshape(b * h * w_, k), w).reshape(b, h, w_, w.shape[1])


def _pw4d_n64_fwd(x4, w, interpret):
    return _pw4d_n64(x4, w, interpret), (x4, w)


def _pw4d_n64_bwd(interpret, res, g4):
    x4, w = res
    dx4 = _dgrad_n64(g4, w, interpret=interpret)
    # wgrad stays in XLA-land (canonicalized into its fused conv-wgrad).
    dw = jax.lax.dot_general(
        x4.reshape(-1, x4.shape[-1]),
        g4.reshape(-1, g4.shape[-1]),
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return dx4, dw.astype(w.dtype)


_pw4d_n64.defvjp(_pw4d_n64_fwd, _pw4d_n64_bwd)


def pointwise_conv_n64(x4: jax.Array, kernel2: jax.Array) -> jax.Array:
    """1x1 conv to 64 features with the layout-native Pallas dgrad."""
    return _pw4d_n64(x4, kernel2, not _on_tpu())


def pointwise_conv(x: jax.Array, kernel: jax.Array, strides: int = 1) -> jax.Array:
    """NHWC 1x1 convolution with Pallas backward.

    x: [B, H, W, Cin]; kernel: [1, 1, Cin, Cout] (or [Cin, Cout]).  A strided
    1x1 conv reads only the top-left pixel of each window, so stride-s is a
    spatial slice before the matmul (its VJP scatters zeros back — cheap
    relative to the dgrad it replaces).
    """
    if kernel.ndim == 4:
        kernel = kernel[0, 0]
    if strides > 1:
        x = x[:, ::strides, ::strides, :]
    b, h, w_, cin = x.shape
    y = pointwise_matmul(x.reshape(b * h * w_, cin), kernel)
    return y.reshape(b, h, w_, kernel.shape[1])
