"""Flash attention as a Pallas TPU kernel (forward + custom-VJP backward).

The hot op of the BERT workload (SURVEY.md §7 step 8: "Pallas kernels ...
attention for BERT if MFU < target"). Blockwise online-softmax attention:
O(L) memory instead of materializing the [L, L] score matrix in HBM, with
the K/V stream resident in VMEM and every matmul on the MXU.

Semantics match ``parallel.ring_attention.dense_attention`` exactly (same
layout ``[B, L, H, D]``, same key-padding-mask contract, f32 accumulation) —
the equivalence test in tests/test_flash_attention.py pins it. Ring
composition is implemented, not just possible: ``flash_attention_block``
returns (o, lse) per K/V block and ``ring_attention(..., inner="flash")``
merges the streamed blocks by logsumexp (ring = outer loop over ICI,
flash = inner loop over VMEM; tests/test_ring_attention.py pins the
composition against dense attention, gradients included).

Kernel structure (one (batch, head, q-block) program per grid point):
  fwd:  stream K/V blocks from VMEM, online softmax, save per-row logsumexp
  bwd:  dQ pass gridded over q-blocks; dK/dV pass gridded over k-blocks;
        both recompute P from the saved logsumexp (no [L,L] residual)

Two kernel families share that structure (``packing=`` selects; None=auto):
  "bh"   — operands transposed to [B*H, L, D] in HBM (4 relayouts per
           layer-direction, ~200 GB/s copies; measured 11.9 ms/step at the
           L=512 b=32 BERT config before r5).
  "flat" — r5: operands stay FLAT [B, L, H*D] (the layout the surrounding
           projections produce/consume — zero HBM relayouts); the kernel
           isolates heads by lane-masking aligned 128-lane tiles, which
           costs no extra MXU passes. Measured at BERT-base production
           geometry (b=32, L=512): fwd 0.862 -> 0.648 ms, fwd+bwd
           2.395 -> 1.780 ms per layer vs "bh". See the packed-section
           comment below for the masking identity and its constraints.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG = -1e30
# Base-2 softmax domain (r5): folding log2(e) into the score scale turns
# every VPU exp into the cheaper exp2 — measured 4% off the fwd kernel
# (1.043 -> 0.998 ms at bh=576, L=512) at |o| diff <= 1 bf16 ulp. The
# saved lse stays in NATURAL log (public contract for the ring merge);
# kernels convert at their boundaries.
_LOG2E = math.log2(math.e)
# Block defaults re-swept in r5 at the production geometry (bh=576, L=512,
# D=64 — the L=512 b=48 BERT config): bq = bk = 512 wins every kernel
# (fwd 1.145 -> 1.01 ms, dq 1.164 -> 0.894, dkv 1.639 -> 1.109 per layer;
# /tmp-sweep recorded in docs/PERF.md r5). At L <= 512 that means ONE
# whole-sequence tile per program — fewer programs, zero online-softmax
# rescale rounds; at longer L the q/k loops re-engage with 512-sized
# blocks (the r3 L=2048 sweep also preferred 512/512).
_DEFAULT_BLOCK_Q = 512
_DEFAULT_BLOCK_K = 512


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _fit_block(default: int, l: int) -> int:
    """Largest block size <= default that divides l (lane-friendly steps).

    Ring/Ulysses shard lengths are not always powers of two (e.g. a ring
    shard of L_local = 384 fits 192-blocks): clamping to the default and
    demanding divisibility would reject valid geometries the einsum inner
    handles. Only multiple-of-8 blocks are accepted (the sublane floor);
    a length with no such divisor still raises — silently falling back to
    one l-sized block would defeat the blocking for large shards (a
    [l, l] f32 score tile in VMEM) instead of surfacing the geometry error.
    """
    b = min(default, l)
    if b >= 8 and b % 8 == 0 and l % b == 0:
        return b
    b -= b % 8
    while b >= 8 and l % b:
        b -= 8
    if b >= 8 and l % b == 0:
        return b
    raise ValueError(
        f"block length {l} has no multiple-of-8 divisor <= {default}; "
        "pad the shard or pick a different ring size"
    )


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref, *, block_k, scale):
    # q_ref: [BQ, D]; k_ref/v_ref: [L, D]; mask_ref: [1, L]; o: [BQ, D];
    # lse: [1, BQ]. One program per (b*h, q-block).
    #
    # MXU discipline: operands stay in their storage dtype (bf16) with f32
    # accumulation via preferred_element_type — casting inputs to f32 first
    # would force 8x-slower f32 systolic passes (the r2 kernel's mistake;
    # dense attention never paid it). P is cast back to the value dtype for
    # the PV matmul, exactly like the dense path's p.astype(v.dtype).
    bq, d = q_ref.shape
    l = k_ref.shape[0]
    q = q_ref[:]

    def body(j, carry):
        o, m, denom = carry
        k_blk = k_ref[pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[pl.ds(j * block_k, block_k), :]
        # Scores land directly in the base-2 domain (scale * log2e folded).
        s = (scale * _LOG2E) * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BQ, BK] f32
        mask_blk = mask_ref[0, pl.ds(j * block_k, block_k)]
        s = jnp.where(mask_blk[None, :] != 0, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp2(s - m_new[:, None])
        p = p * mask_blk[None, :]
        corr = jnp.exp2(m - m_new)
        denom = denom * corr + jnp.sum(p, axis=-1)
        o = o * corr[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype),
            v_blk,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return o, m_new, denom

    o = jnp.zeros((bq, d), jnp.float32)
    m = jnp.full((bq,), _NEG, jnp.float32)
    denom = jnp.zeros((bq,), jnp.float32)
    o, m, denom = jax.lax.fori_loop(0, l // block_k, body, (o, m, denom))
    safe = jnp.maximum(denom, 1e-37)
    o_ref[:] = (o / safe[:, None]).astype(o_ref.dtype)
    # Natural-log logsumexp per query row (ln(denom * 2^m)); fully-masked
    # rows get _NEG (o stays 0).
    lse_ref[0, :] = jnp.where(denom > 0, m / _LOG2E + jnp.log(safe), _NEG)


def _fwd(q, k, v, mask, block_q, block_k, interpret):
    bh, l, d = q.shape
    scale = d**-0.5
    grid = (bh, l // block_q)
    kernel = functools.partial(_fwd_kernel, block_k=block_k, scale=scale)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, l, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, l, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, 1, l), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, l, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, l), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, mask)
    return o, lse.reshape(bh, l)


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref, dq_ref, *, block_k, scale
):
    bq, d = q_ref.shape
    l = k_ref.shape[0]
    q = q_ref[:]
    do = do_ref[:]
    lse = lse_ref[0, :]
    delta = delta_ref[0, :]

    def body(j, dq):
        k_blk = k_ref[pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[pl.ds(j * block_k, block_k), :]
        mask_blk = mask_ref[0, pl.ds(j * block_k, block_k)]
        # P recomputed in the base-2 domain (see _fwd_kernel); the natural-
        # domain derivative ds = p * (dp - delta) is unchanged.
        s = (scale * _LOG2E) * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        # Mask in the SCALED domain: a fully-masked row carries lse = _NEG
        # (natural log), so the recompute must cancel _NEG * _LOG2E against
        # _NEG * _LOG2E exactly — masking with plain _NEG would make the
        # difference +4e29 and exp2 of it inf (NaN after the mask multiply).
        s = jnp.where(mask_blk[None, :] != 0, s, _NEG * _LOG2E)
        p = jnp.exp2(s - (_LOG2E * lse)[:, None]) * mask_blk[None, :]
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None])
        return dq + jax.lax.dot_general(
            ds.astype(k_blk.dtype),
            k_blk,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq = jnp.zeros((bq, d), jnp.float32)
    dq = jax.lax.fori_loop(0, l // block_k, body, dq)
    dq_ref[:] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, block_q, scale,
):
    bk, d = k_ref.shape
    l = q_ref.shape[0]
    k = k_ref[:]
    v = v_ref[:]
    j = pl.program_id(1)
    mask_blk = mask_ref[0, pl.ds(j * bk, bk)]

    def body(i, carry):
        dk, dv = carry
        q_blk = q_ref[pl.ds(i * block_q, block_q), :]
        do_blk = do_ref[pl.ds(i * block_q, block_q), :]
        lse_blk = lse_ref[0, pl.ds(i * block_q, block_q)]
        delta_blk = delta_ref[0, pl.ds(i * block_q, block_q)]
        s = (scale * _LOG2E) * jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BQ, BK] base-2 domain (see _fwd_kernel)
        # Scaled-domain mask value — see _bwd_dq_kernel.
        s = jnp.where(mask_blk[None, :] != 0, s, _NEG * _LOG2E)
        p = jnp.exp2(s - (_LOG2E * lse_blk)[:, None]) * mask_blk[None, :]
        p_lo = p.astype(do_blk.dtype)
        dv = dv + jax.lax.dot_general(
            p_lo, do_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_blk[:, None])
        dk = dk + jax.lax.dot_general(
            ds.astype(q_blk.dtype),
            q_blk,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk, dv

    dk = jnp.zeros((bk, d), jnp.float32)
    dv = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, l // block_q, body, (dk, dv))
    dk_ref[:] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _bwd_impl(block_q, block_k, interpret, residuals, do, dlse=None):
    """Shared backward: flash-attention kernels over saved (q, k, v, lse).

    ``dlse`` (the logsumexp cotangent, used by the ring-composable block op
    whose lse output feeds the cross-block merge) folds into the delta term:
    dL/ds_ij = p_ij (dp_ij - delta_i) + p_ij dlse_i, so passing
    delta' = delta - dlse to the unchanged kernels is the exact extension.
    """
    q, k, v, mask, o, lse = residuals
    bh, l, d = q.shape
    scale = d**-0.5
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)  # [bh,l]
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
    delta = delta.reshape(bh, 1, l)
    lse3 = lse.reshape(bh, 1, l)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_k=block_k, scale=scale),
        grid=(bh, l // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, l, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, l, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, 1, l), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, 1, block_q), lambda b, i: (b, 0, i)),
            pl.BlockSpec((None, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, l, d), q.dtype),
        interpret=interpret,
    )(q, k, v, mask, do, lse3, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=block_q, scale=scale),
        grid=(bh, l // block_k),
        in_specs=[
            pl.BlockSpec((None, l, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((None, 1, l), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((None, l, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((None, 1, l), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((None, 1, l), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, l, d), k.dtype),
            jax.ShapeDtypeStruct((bh, l, d), v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, mask, do, lse3, delta)
    return dq, dk, dv, None


def _bwd(block_q, block_k, interpret, residuals, g):
    return _bwd_impl(block_q, block_k, interpret, residuals, g)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash(q, k, v, mask, block_q, block_k, interpret):
    o, _ = _fwd(q, k, v, mask, block_q, block_k, interpret)
    return o


def _flash_fwd(q, k, v, mask, block_q, block_k, interpret):
    o, lse = _fwd(q, k, v, mask, block_q, block_k, interpret)
    return o, (q, k, v, mask, o, lse)


_flash.defvjp(_flash_fwd, _bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_block(q, k, v, mask, block_q, block_k, interpret):
    return _fwd(q, k, v, mask, block_q, block_k, interpret)


def _flash_block_fwd(q, k, v, mask, block_q, block_k, interpret):
    o, lse = _fwd(q, k, v, mask, block_q, block_k, interpret)
    return (o, lse), (q, k, v, mask, o, lse)


def _flash_block_bwd(block_q, block_k, interpret, residuals, g):
    do, dlse = g
    return _bwd_impl(block_q, block_k, interpret, residuals, do, dlse)


_flash_block.defvjp(_flash_block_fwd, _flash_block_bwd)


# ---------------------------------------------------------------------------
# Packed (layout-native) kernels — r5
# ---------------------------------------------------------------------------
#
# The bh-major kernels above require [B*H, L, D], which costs four HBM
# relayouts per layer-direction ([B,L,H,D] <-> [B,H,L,D] for q/k/v in and
# o out, again in backward) — measured 11.9 ms/step at the shipped L=512
# b=32 BERT config, ~200 GB/s copies the bucket table files under "other"
# (docs/PERF.md r5). A head-minor BlockSpec ((1, bq, H, D)) was built and
# rejected: (H=12, D=64) minor dims violate the (8,128) tile rule and
# Mosaic pads 12->16 x 64->128 on every operand.
#
# This variant threads the needle: operands stay FLAT [B, L, H*D] — the
# exact layout the surrounding projections produce and consume, and
# (8,128)-clean since H*D = 768. Heads are separated WITHOUT lane slicing
# (Mosaic also rejects sub-128 lane-offset loads: "cannot statically prove
# that index in dimension 2 is a multiple of 128" — measured this round):
# the kernel loads aligned 128-lane tiles holding 128/D heads each and
# isolates head h by LANE MASKING the q (resp. do/ds) operand before the
# matmul. Because MXU contraction and output tiles are 128 wide, a masked
# 128-wide matmul costs exactly the same systolic passes as the bh
# kernels' 64-wide one — the mask just zeroes the cross-head terms:
#   (q * mask_h) @ k_tile^T == q_h @ k_h^T            (contraction side)
#   (p_h @ v_tile) * mask_h == p_h @ v_h  in h's lanes (output side)
# so the per-head math is exactly the bh kernels'; only the addressing
# changed. VMEM per program: q/k/v/o blocks at bq = bk = L = 512 total
# ~4 MB of the ~16 MB budget. The lse contract also improves: the kernel
# writes [B, H, L] natural-log lse directly (what the ring merge wants).


def _lane_masks(d: int, dtype):
    """Per-head lane masks for one 128-lane tile holding 128//d heads."""
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)
    return [
        ((lane >= e * d) & (lane < (e + 1) * d)).astype(dtype)
        for e in range(128 // d)
    ]


def _fwd_kernel_packed(
    q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref, *, block_k, scale, heads
):
    # q_ref: [BQ, HD]; k_ref/v_ref: [L, HD]; mask_ref: [1, L];
    # o_ref: [BQ, HD]; lse_ref: FULL [H, L] (each program writes its
    # q-range — an L-sized lane slice per q-block would break the
    # 128-lane rule for small blocks). One program per (batch, q-block).
    bq, hd = q_ref.shape
    l = k_ref.shape[0]
    d = hd // heads
    hpt = 128 // d  # heads per 128-lane tile
    qi = pl.program_id(1)
    for t in range(hd // 128):
        q_t = q_ref[:, 128 * t : 128 * (t + 1)]
        msks = _lane_masks(d, q_t.dtype)
        q_heads = [q_t * msks[e] for e in range(hpt)]

        def body(j, carry, t=t, q_heads=q_heads):
            k_t = k_ref[pl.ds(j * block_k, block_k), 128 * t : 128 * (t + 1)]
            v_t = v_ref[pl.ds(j * block_k, block_k), 128 * t : 128 * (t + 1)]
            mask_blk = mask_ref[0, pl.ds(j * block_k, block_k)]
            out = []
            for e in range(hpt):
                o, m, denom = carry[e]
                # Contraction over all 128 lanes of the masked q is
                # exactly q_h @ k_h^T: the mask zeroes other heads' terms.
                s = (scale * _LOG2E) * jax.lax.dot_general(
                    q_heads[e], k_t, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                s = jnp.where(mask_blk[None, :] != 0, s, _NEG)
                m_new = jnp.maximum(m, jnp.max(s, axis=-1))
                p = jnp.exp2(s - m_new[:, None])
                p = p * mask_blk[None, :]
                corr = jnp.exp2(m - m_new)
                denom = denom * corr + jnp.sum(p, axis=-1)
                # p @ v_tile: head h's lanes carry p_h @ v_h; other heads'
                # lanes carry garbage that the write-combine masks off.
                o = o * corr[:, None] + jax.lax.dot_general(
                    p.astype(v_t.dtype),
                    v_t,
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                out.append((o, m_new, denom))
            return tuple(out)

        init = tuple(
            (
                jnp.zeros((bq, 128), jnp.float32),
                jnp.full((bq,), _NEG, jnp.float32),
                jnp.zeros((bq,), jnp.float32),
            )
            for _ in range(hpt)
        )
        carry = jax.lax.fori_loop(0, l // block_k, body, init)
        o_tile = jnp.zeros((bq, 128), jnp.float32)
        for e in range(hpt):
            o, m, denom = carry[e]
            safe = jnp.maximum(denom, 1e-37)
            o_tile = o_tile + (o / safe[:, None]) * msks[e].astype(jnp.float32)
            lse_ref[t * hpt + e, pl.ds(qi * bq, bq)] = jnp.where(
                denom > 0, m / _LOG2E + jnp.log(safe), _NEG
            )
        o_ref[:, 128 * t : 128 * (t + 1)] = o_tile.astype(o_ref.dtype)


def _fwd_packed(q, k, v, mask, heads, block_q, block_k, interpret):
    b, l, hd = q.shape
    scale = (hd // heads) ** -0.5
    o, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel_packed, block_k=block_k, scale=scale, heads=heads
        ),
        grid=(b, l // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, hd), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, l, hd), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, l, hd), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, 1, l), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, hd), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, heads, l), lambda b, i: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, l, hd), q.dtype),
            jax.ShapeDtypeStruct((b, heads, l), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, mask)
    return o, lse


def _bwd_dq_kernel_packed(
    q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref, dq_ref,
    *, block_k, scale, heads,
):
    # q/do/dq: [BQ, HD]; k/v: [L, HD]; mask: [1, L]; lse/delta: FULL [H, L]
    # (sliced per program — see _fwd_kernel_packed).
    bq, hd = q_ref.shape
    l = k_ref.shape[0]
    d = hd // heads
    hpt = 128 // d
    qi = pl.program_id(1)
    for t in range(hd // 128):
        q_t = q_ref[:, 128 * t : 128 * (t + 1)]
        do_t = do_ref[:, 128 * t : 128 * (t + 1)]
        msks = _lane_masks(d, q_t.dtype)
        q_heads = [q_t * msks[e] for e in range(hpt)]
        do_heads = [do_t * msks[e] for e in range(hpt)]
        lses = [
            lse_ref[t * hpt + e, pl.ds(qi * bq, bq)] for e in range(hpt)
        ]
        deltas = [
            delta_ref[t * hpt + e, pl.ds(qi * bq, bq)] for e in range(hpt)
        ]

        def body(j, dqs, t=t, q_heads=q_heads, do_heads=do_heads,
                 lses=lses, deltas=deltas):
            k_t = k_ref[pl.ds(j * block_k, block_k), 128 * t : 128 * (t + 1)]
            v_t = v_ref[pl.ds(j * block_k, block_k), 128 * t : 128 * (t + 1)]
            mask_blk = mask_ref[0, pl.ds(j * block_k, block_k)]
            out = []
            for e in range(hpt):
                s = (scale * _LOG2E) * jax.lax.dot_general(
                    q_heads[e], k_t, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                # Scaled-domain mask value — see _bwd_dq_kernel.
                s = jnp.where(mask_blk[None, :] != 0, s, _NEG * _LOG2E)
                p = (
                    jnp.exp2(s - (_LOG2E * lses[e])[:, None])
                    * mask_blk[None, :]
                )
                dp = jax.lax.dot_general(
                    do_heads[e], v_t, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                ds = p * (dp - deltas[e][:, None])
                # ds @ k_tile: head h's lanes carry ds_h @ k_h; the
                # write-combine below masks the rest.
                out.append(
                    dqs[e]
                    + jax.lax.dot_general(
                        ds.astype(k_t.dtype),
                        k_t,
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                )
            return tuple(out)

        init = tuple(jnp.zeros((bq, 128), jnp.float32) for _ in range(hpt))
        dqs = jax.lax.fori_loop(0, l // block_k, body, init)
        dq_tile = jnp.zeros((bq, 128), jnp.float32)
        for e in range(hpt):
            dq_tile = dq_tile + dqs[e] * msks[e].astype(jnp.float32)
        dq_ref[:, 128 * t : 128 * (t + 1)] = (dq_tile * scale).astype(
            dq_ref.dtype
        )


def _bwd_dkv_kernel_packed(
    q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, block_q, scale, heads,
):
    # k/v/dk/dv: [BK, HD]; q/do: [L, HD]; mask/lse/delta: FULL [1|H, L].
    bk, hd = k_ref.shape
    l = q_ref.shape[0]
    d = hd // heads
    hpt = 128 // d
    kj = pl.program_id(1)
    mask_blk = mask_ref[0, pl.ds(kj * bk, bk)]
    for t in range(hd // 128):
        k_t = k_ref[:, 128 * t : 128 * (t + 1)]
        v_t = v_ref[:, 128 * t : 128 * (t + 1)]
        msks = _lane_masks(d, k_t.dtype)

        def body(i, carry, t=t, k_t=k_t, v_t=v_t, msks=msks):
            q_blk = q_ref[pl.ds(i * block_q, block_q), 128 * t : 128 * (t + 1)]
            do_blk = do_ref[
                pl.ds(i * block_q, block_q), 128 * t : 128 * (t + 1)
            ]
            out = []
            for e in range(hpt):
                dk, dv = carry[e]
                lse_blk = lse_ref[t * hpt + e, pl.ds(i * block_q, block_q)]
                delta_blk = delta_ref[
                    t * hpt + e, pl.ds(i * block_q, block_q)
                ]
                q_h = q_blk * msks[e]
                s = (scale * _LOG2E) * jax.lax.dot_general(
                    q_h, k_t, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                s = jnp.where(mask_blk[None, :] != 0, s, _NEG * _LOG2E)
                p = (
                    jnp.exp2(s - (_LOG2E * lse_blk)[:, None])
                    * mask_blk[None, :]
                )
                p_lo = p.astype(do_blk.dtype)
                # p^T @ do_tile: head h's lanes carry p_h^T @ do_h
                # (garbage elsewhere, masked in the write-combine).
                dv = dv + jax.lax.dot_general(
                    p_lo, do_blk, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                dp = jax.lax.dot_general(
                    do_blk * msks[e], v_t, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                ds = p * (dp - delta_blk[:, None])
                dk = dk + jax.lax.dot_general(
                    ds.astype(q_blk.dtype),
                    q_blk,
                    (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                out.append((dk, dv))
            return tuple(out)

        init = tuple(
            (
                jnp.zeros((bk, 128), jnp.float32),
                jnp.zeros((bk, 128), jnp.float32),
            )
            for _ in range(hpt)
        )
        carry = jax.lax.fori_loop(0, l // block_q, body, init)
        dk_tile = jnp.zeros((bk, 128), jnp.float32)
        dv_tile = jnp.zeros((bk, 128), jnp.float32)
        for e in range(hpt):
            dk, dv = carry[e]
            f32m = msks[e].astype(jnp.float32)
            dk_tile = dk_tile + dk * f32m
            dv_tile = dv_tile + dv * f32m
        dk_ref[:, 128 * t : 128 * (t + 1)] = (dk_tile * scale).astype(
            dk_ref.dtype
        )
        dv_ref[:, 128 * t : 128 * (t + 1)] = dv_tile.astype(dv_ref.dtype)


def _bwd_impl_packed(heads, block_q, block_k, interpret, residuals, do, dlse=None):
    """Packed backward. ``dlse`` folds into delta exactly as in _bwd_impl."""
    q, k, v, mask, o, lse = residuals  # lse: [B, H, L]
    b, l, hd = q.shape
    d = hd // heads
    scale = d**-0.5
    # Per-head delta_i = sum_d do*o — [B, L, H] reduce, then head-major.
    delta = (
        (do.astype(jnp.float32) * o.astype(jnp.float32))
        .reshape(b, l, heads, d)
        .sum(axis=-1)
        .transpose(0, 2, 1)
    )  # [B, H, L] — small (B*H*L f32), the transpose is noise next to qkv
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel_packed, block_k=block_k, scale=scale, heads=heads
        ),
        grid=(b, l // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, hd), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, l, hd), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, l, hd), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, 1, l), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, block_q, hd), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, heads, l), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, heads, l), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, hd), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, l, hd), q.dtype),
        interpret=interpret,
    )(q, k, v, mask, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel_packed, block_q=block_q, scale=scale, heads=heads
        ),
        grid=(b, l // block_k),
        in_specs=[
            pl.BlockSpec((None, l, hd), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((None, block_k, hd), lambda b, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, hd), lambda b, j: (b, j, 0)),
            pl.BlockSpec((None, 1, l), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((None, l, hd), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((None, heads, l), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((None, heads, l), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, hd), lambda b, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, hd), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, l, hd), k.dtype),
            jax.ShapeDtypeStruct((b, l, hd), v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, mask, do, lse, delta)
    return dq, dk, dv, None


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_packed(q, k, v, mask, heads, block_q, block_k, interpret):
    o, _ = _fwd_packed(q, k, v, mask, heads, block_q, block_k, interpret)
    return o


def _flash_packed_fwd(q, k, v, mask, heads, block_q, block_k, interpret):
    o, lse = _fwd_packed(q, k, v, mask, heads, block_q, block_k, interpret)
    return o, (q, k, v, mask, o, lse)


def _flash_packed_bwd(heads, block_q, block_k, interpret, residuals, g):
    return _bwd_impl_packed(heads, block_q, block_k, interpret, residuals, g)


_flash_packed.defvjp(_flash_packed_fwd, _flash_packed_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_block_packed(q, k, v, mask, heads, block_q, block_k, interpret):
    return _fwd_packed(q, k, v, mask, heads, block_q, block_k, interpret)


def _flash_block_packed_fwd(q, k, v, mask, heads, block_q, block_k, interpret):
    o, lse = _fwd_packed(q, k, v, mask, heads, block_q, block_k, interpret)
    return (o, lse), (q, k, v, mask, o, lse)


def _flash_block_packed_bwd(heads, block_q, block_k, interpret, residuals, g):
    do, dlse = g
    return _bwd_impl_packed(
        heads, block_q, block_k, interpret, residuals, do, dlse
    )


_flash_block_packed.defvjp(_flash_block_packed_fwd, _flash_block_packed_bwd)


def _packing_ok(h: int, d: int) -> bool:
    """Packed-path geometry: whole heads must tile 128-lane groups — D a
    divisor of 128 (64 for BERT-base: two heads per tile) and H*D a
    multiple of 128. Covers tp shards with an even local head count
    (12, 6, 4, 2 heads at D=64); odd shards (tp=4 -> 3 heads, 192 lanes)
    fall back to the bh kernels."""
    return d <= 128 and 128 % d == 0 and (h * d) % 128 == 0


def _flat_vmem_est(l, hd, block_q, block_k, esize=2) -> int:
    """Rough VMEM bytes for one packed-kernel program: the K/V streams stay
    RESIDENT at full [L, H*D] (double-buffered by Mosaic) — 12x the bh
    kernels' per-head residency, which is what caps the packed path's L."""
    kv = 2 * 2 * l * hd * esize          # k + v, double-buffered
    blocks = 3 * block_q * hd * esize     # q/o/do-class blocks
    scores = block_q * block_k * 4        # one f32 score tile
    carries = 6 * block_q * 128 * 4       # per-tile o/m/denom f32 carries
    return kv + blocks + scores + carries


# Measured on this chip: l=2048 hd=768 blows the 16 MB scoped-vmem budget
# (Mosaic: 18.21M requested); l <= 1024 at hd=768 fits. 14 MB keeps margin.
_FLAT_VMEM_LIMIT = 14 * 1024 * 1024


def _flat_auto(h, d, block_q, block_k, interpret, l=0, esize=2) -> bool:
    # Compiled-mode lane slices (lse/delta/mask at block offsets) need
    # 128-aligned blocks; interpret mode has no such constraint. ``esize``
    # is the operand element size — f32 K/V streams are twice the bf16
    # residency, so the auto rule must see the real dtype or it selects
    # 'flat' at geometries that blow the scoped-vmem budget.
    if not _packing_ok(h, d):
        return False
    if interpret:
        return True
    if block_q % 128 or block_k % 128:
        return False
    return _flat_vmem_est(l, h * d, block_q, block_k, esize) <= _FLAT_VMEM_LIMIT


def _require_flat(h, d, block_q, block_k, interpret, l=0, esize=2) -> None:
    """Loud guard for EXPLICIT packing="flat": an unsupported geometry must
    not reach the kernels — the head loop covers only hd//128 lane tiles, so
    e.g. H*D=192 leaves lanes 128-191 unread and returns garbage (silently
    in interpret mode; as an opaque Mosaic internal error compiled)."""
    if not _packing_ok(h, d):
        raise ValueError(
            f"packing='flat' needs whole heads tiling 128-lane groups "
            f"(D | 128 and H*D % 128 == 0); got H={h}, D={d}. "
            "Use packing='bh' or None (auto)."
        )
    if not interpret and (block_q % 128 or block_k % 128):
        raise ValueError(
            f"packing='flat' compiled for TPU needs 128-aligned blocks "
            f"(lane-slice rule); got block_q={block_q}, block_k={block_k}. "
            "Use packing='bh' or None (auto)."
        )
    if not interpret and (
        _flat_vmem_est(l, h * d, block_q, block_k, esize) > _FLAT_VMEM_LIMIT
    ):
        raise ValueError(
            f"packing='flat' keeps K/V resident at [L={l}, H*D={h * d}] in "
            f"VMEM — past the ~16 MB budget at this geometry (est "
            f"{_flat_vmem_est(l, h * d, block_q, block_k, esize) >> 20} MB). "
            "Use packing='bh' or None (auto)."
        )


def flash_attention_block(
    q,
    k,
    v,
    mask=None,
    *,
    block_q: int = _DEFAULT_BLOCK_Q,
    block_k: int = _DEFAULT_BLOCK_K,
    interpret: bool | None = None,
    packing: str | None = None,
):
    """One flash block with its logsumexp: the ring's inner step.

    Layout ``[B, L, H, D]`` like :func:`flash_attention`, but L must already
    be a multiple of both blocks (ring shards are) and the return is
    ``(o [B, L, H, D], lse [B, H, L])`` — block-normalized output plus the
    per-row logsumexp, which parallel/ring_attention.py uses to merge blocks
    exactly (numerically stable weighted combine). Differentiable in both
    outputs (the lse cotangent rides the same backward kernels).

    ``packing``: ``"flat"`` (layout-native packed kernels, the r5 default
    where head geometry allows — see module comment), ``"bh"`` (the
    transpose-into-[B*H, L, D] kernels), or None for the auto rule.
    """
    if interpret is None:
        interpret = _use_interpret()
    b, l, h, d = q.shape
    # The ring streams fixed-length shards — no padding allowed here, so fit
    # the blocks to the shard length instead (largest divisor <= default).
    block_q = _fit_block(block_q, l)
    block_k = _fit_block(block_k, l)
    if mask is None:
        mask = jnp.ones((b, l), bool)
    if packing is None:
        packing = (
            "flat"
            if _flat_auto(
                h, d, block_q, block_k, interpret, l, q.dtype.itemsize
            )
            else "bh"
        )
    elif packing == "flat":
        _require_flat(h, d, block_q, block_k, interpret, l, q.dtype.itemsize)

    if packing == "flat":
        mask_f = mask.astype(jnp.float32).reshape(b, 1, l)
        o, lse = _flash_block_packed(
            q.reshape(b, l, h * d),
            k.reshape(b, l, h * d),
            v.reshape(b, l, h * d),
            mask_f,
            h,
            block_q,
            block_k,
            interpret,
        )
        return o.reshape(b, l, h, d), lse

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, l, d)

    mask_bh = jnp.repeat(mask.astype(jnp.float32), h, axis=0).reshape(b * h, 1, l)
    o, lse = _flash_block(
        to_bh(q), to_bh(k), to_bh(v), mask_bh, block_q, block_k, interpret
    )
    o = o.reshape(b, h, l, d).transpose(0, 2, 1, 3)
    return o, lse.reshape(b, h, l)


def flash_attention(
    q,
    k,
    v,
    mask=None,
    *,
    block_q: int = _DEFAULT_BLOCK_Q,
    block_k: int = _DEFAULT_BLOCK_K,
    interpret: bool | None = None,
    packing: str | None = None,
):
    """Exact attention, flash-style. Layout ``[B, L, H, D]``, mask ``[B, L]``.

    Pads L up to a block multiple internally (padded keys masked out, padded
    query rows sliced off). ``interpret=None`` auto-selects interpreter mode
    off-TPU so tests run on CPU. ``packing`` as in
    :func:`flash_attention_block` (None = auto: layout-native packed kernels
    when the head geometry is lane-aligned, else the bh-major kernels).
    """
    if interpret is None:
        interpret = _use_interpret()
    b, l, h, d = q.shape
    block_q = min(block_q, max(l, 8))
    block_k = min(block_k, max(l, 8))
    # Pad to a common multiple of BOTH blocks: padding to only the larger one
    # leaves trailing q rows outside the grid (uninitialized output).
    step = math.lcm(block_q, block_k)
    l_pad = -(-l // step) * step
    if mask is None:
        mask = jnp.ones((b, l), bool)
    if l_pad != l:
        pad = ((0, 0), (0, l_pad - l), (0, 0), (0, 0))
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
        mask = jnp.pad(mask, ((0, 0), (0, l_pad - l)))
    if packing is None:
        packing = (
            "flat"
            if _flat_auto(
                h, d, block_q, block_k, interpret, l_pad, q.dtype.itemsize
            )
            else "bh"
        )
    elif packing == "flat":
        _require_flat(
            h, d, block_q, block_k, interpret, l_pad, q.dtype.itemsize
        )

    if packing == "flat":
        mask_f = mask.astype(jnp.float32).reshape(b, 1, l_pad)
        o = _flash_packed(
            q.reshape(b, l_pad, h * d),
            k.reshape(b, l_pad, h * d),
            v.reshape(b, l_pad, h * d),
            mask_f,
            h,
            block_q,
            block_k,
            interpret,
        )
        return o.reshape(b, l_pad, h, d)[:, :l]

    # [B, L, H, D] -> [B*H, L, D]
    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, l_pad, d)

    qh, kh, vh = to_bh(q), to_bh(k), to_bh(v)
    mask_bh = jnp.repeat(mask.astype(jnp.float32), h, axis=0).reshape(
        b * h, 1, l_pad
    )
    o = _flash(qh, kh, vh, mask_bh, block_q, block_k, interpret)
    o = o.reshape(b, h, l_pad, d).transpose(0, 2, 1, 3)
    return o[:, :l]
