"""Pallas TPU kernels for the hot ops.

The reference's "native layer" is the TF-1.x CUDA runtime it drives
(SURVEY.md §2 native-component table); in this rebuild the sanctioned native
compute layer on TPU is Pallas. Kernels here are drop-in replacements for
their XLA-composed equivalents, exact to f32-accumulation tolerance, with
``interpret=True`` fallbacks so every kernel is CI-testable on CPU.
"""

from distributed_tensorflow_tpu.ops.flash_attention import (  # noqa: F401
    flash_attention,
    flash_attention_block,
)
from distributed_tensorflow_tpu.ops.fused_conv_bn import (  # noqa: F401
    conv1x1_bn_act,
    fused_supported,
    fused_unit,
)
from distributed_tensorflow_tpu.ops.pointwise_conv import (  # noqa: F401
    pointwise_conv,
    pointwise_matmul,
)
