"""Async sharded TrainState checkpointing (orbax/tensorstore backend).

Capability parity (SURVEY.md §5): periodic save + restore-latest-on-restart,
including optimizer slots and the stale-mode gradient ring buffer, so a
resumed async-stale run continues bit-exactly where it left off — something
the reference's true-async PS could never guarantee.
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp

logger = logging.getLogger(__name__)


class Checkpointer:
    """Periodic async checkpoint manager for :class:`TrainState` pytrees.

    Usage::

        ckpt = Checkpointer(dir, max_to_keep=3)
        state, start = ckpt.restore_latest(state)   # no-op on fresh dirs
        fit(state, step, data, checkpointer=ckpt, ckpt_every=500, ...)
        ckpt.close()
    """

    def __init__(
        self,
        directory: str | Path,
        *,
        max_to_keep: int = 3,
        use_async: bool = True,
        fault_injector=None,
    ):
        self._injector = fault_injector
        self._mgr = ocp.CheckpointManager(
            Path(directory).absolute(),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=use_async,
            ),
        )

    def save(self, step: int, state: Any, *, force: bool = False) -> None:
        """Queue an async save of ``state`` at ``step`` (non-blocking).

        ``fault_injector`` (train/faultinject.py) may raise a scheduled
        ``ckpt_write_error`` here — the transient-storage failure class
        ``train/resilience.py``'s save wrapper absorbs.
        """
        if self._injector is not None:
            self._injector.check_ckpt_save(step)
        self._mgr.save(step, args=ocp.args.StandardSave(state), force=force)

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore_latest(self, state: Any) -> tuple[Any, int]:
        """Restore the newest checkpoint into ``state``'s structure/shardings.

        ``state`` may be a live TrainState (used as the abstract template —
        its shardings are preserved) or an abstract pytree of
        ``jax.ShapeDtypeStruct``. Returns ``(state, start_step)``;
        ``(state, 0)`` untouched when no checkpoint exists — the
        MonitoredTrainingSession fresh-start behavior.
        """
        step = self._mgr.latest_step()
        if step is None:
            return state, 0
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if isinstance(x, jax.Array)
            else x,
            state,
        )
        restored = self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))
        logger.info("restored checkpoint at step %d", step)
        return restored, step

    def wait(self) -> None:
        """Block until queued async saves are durable (for tests/shutdown)."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def restore_serving_state(
    directory: str | Path,
    template_state: Any,
    *,
    release_opt_state: bool = True,
    weight_dtype: str | None = None,
    memory=None,
    recorder=None,
):
    """Load the newest training checkpoint for the INFERENCE engine.

    ``template_state`` is a TrainState built exactly like the training run's
    (same optimizer/staleness, so the pytree structure matches the saved
    one); its arrays carry the SERVING placements — tensorstore reshards on
    read, in either direction: a TP/PP-sharded training checkpoint restores
    cleanly onto a replicated single-host serving mesh, and a template built
    for a mesh-sharded serving layout (``place_state`` with the engine's
    ``bert_param_specs``-derived state specs, cli/serve.py) has every shard
    read DIRECTLY into its target device — no single-device staging
    round-trip, so restore memory stays bounded by one shard per chip even
    for models too big for one chip. Returns ``(params, model_state,
    step)``. Raises ``FileNotFoundError`` when the directory holds no
    checkpoint: serving must never silently answer from random init.

    ``weight_dtype`` quantizes (``"int8"``: per-channel absmax packing, see
    models/quant.py) or casts (``"bfloat16"``) the restored params BEFORE
    returning, and deletes every replaced fp32 kernel's device buffers —
    checkpoints stay fp32 on disk, the conversion happens at the restore
    boundary, and the reclaimed bytes extend the same released ledger the
    opt-state release writes (component ``weight_quantization``). ``None``
    keeps the checkpoint dtype.

    ``release_opt_state=True`` (the default) deletes the restored optimizer
    slots' and gradient ring's device buffers before returning — serving
    never reads them, and for an AdamW checkpoint they are 2x the params.
    The reclaimed HBM is what a decode engine's KV-cache pages live in, so
    leaving them resident would shrink the slot budget for nothing. The
    reclaimed byte count is logged and flows through the memory registry's
    released ledger (``memory``, default: the process-wide registry), so
    ``GET /memz`` shows the headroom the release bought; ``recorder`` (a
    :class:`~..obs.flightrec.FlightRecorder`) gets a ``ckpt_restore``
    event either way.
    """
    from distributed_tensorflow_tpu.obs.memory import default_registry

    with Checkpointer(directory, use_async=False) as ckpt:
        if ckpt.latest_step() is None:
            raise FileNotFoundError(f"no checkpoint found under {directory}")
        state, step = ckpt.restore_latest(template_state)
    registry = memory if memory is not None else default_registry()
    reclaimed = 0
    if release_opt_state:
        for leaf in jax.tree.leaves((state.opt_state, state.grad_buffer)):
            if isinstance(leaf, jax.Array):
                reclaimed += int(leaf.nbytes)
                leaf.delete()
        # Register-then-release: the bytes land in the released ledger, so
        # /memz shows WHAT was freed, not just a smaller total.
        registry.register("opt_state", reclaimed)
        registry.release("opt_state")
        logger.info(
            "released optimizer state after restore: %.1f MiB reclaimed",
            reclaimed / 2**20,
        )
    params = state.params
    quant_reclaimed = 0
    wd = None
    if weight_dtype is not None:
        from distributed_tensorflow_tpu.models.quant import (
            cast_params,
            free_replaced_leaves,
            normalize_quant_dtype,
            quantize_params,
        )

        wd = normalize_quant_dtype(weight_dtype, "weight_dtype")
        if wd == "int8":
            new_params = quantize_params(params)
        else:
            import jax.numpy as jnp

            new_params = cast_params(params, jnp.dtype(wd))
        # Quantize-then-free: only REPLACED leaves die (embeddings, biases,
        # LayerNorms are shared by identity and survive); the bytes land in
        # the released ledger next to opt_state so /memz shows what the
        # restore-time conversion bought.
        quant_reclaimed = free_replaced_leaves(params, new_params)
        params = new_params
        if quant_reclaimed:
            registry.register("weight_quantization", quant_reclaimed)
            registry.release("weight_quantization")
            logger.info(
                "quantized restored params to %s: %.1f MiB of fp32 "
                "kernels reclaimed", wd, quant_reclaimed / 2**20,
            )
    if recorder is not None:
        recorder.record(
            "ckpt_restore", step=step, release_opt_state=release_opt_state,
            reclaimed_bytes=reclaimed, weight_dtype=wd,
            quant_reclaimed_bytes=quant_reclaimed,
        )
    return params, state.model_state, step
