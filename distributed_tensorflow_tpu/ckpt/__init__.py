"""Checkpoint/resume: async sharded checkpointing over orbax/tensorstore.

Replaces the reference's ``tf.train.Saver`` + ``MonitoredTrainingSession``
auto-restore (SURVEY.md §5 checkpoint row): the chief periodically wrote a
checkpoint; any restarted worker restored the latest. Here saving is
collective (every host participates, arrays written sharded), asynchronous
(off the critical path of the step loop — SURVEY.md §7 hard-part 2), and
restore is just "build the abstract state, load the latest into it".
"""

from distributed_tensorflow_tpu.ckpt.checkpoint import (  # noqa: F401
    Checkpointer,
    restore_serving_state,
)
