"""Ring attention: exact sequence/context-parallel attention over an ICI ring.

The reference has no long-context machinery (SURVEY.md §2 parallelism
inventory: SP/CP "absent — 2017-era TF-1.x harness"); this module is the
framework's first-class TPU-native answer (SURVEY.md §5 long-context row):
shard the sequence over a ``"seq"`` mesh axis and rotate key/value blocks
around the ring with ``lax.ppermute`` — on a TPU torus each hop is a pure
ICI-neighbor transfer that overlaps with the attention block compute.

The math is blockwise (flash-style) online softmax, so the result is *exact*
full attention, not an approximation: each device holds its query shard and
accumulates ``softmax(QK^T)V`` over all key blocks as they stream past,
carrying running max/denominator in f32.

Must run inside a context binding the seq axis (``shard_map`` — the train
step already provides one). Layout: ``[batch, seq_local, heads, head_dim]``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# Finite mask value: -inf would turn exp(-inf + inf) into NaN for
# fully-masked rows; exp(-1e30 - m) == 0 exactly in f32 for any finite m.
_MASK_VALUE = -1e30


def dense_attention(q, k, v, mask=None):
    """Reference single-device attention, same layout/mask contract.

    ``q,k,v: [B, L, H, D]``; ``mask: [B, Lk]`` True = attend (key padding
    mask). Accumulates in f32, returns q.dtype.
    """
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("blhd,bkhd->bhlk", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    if mask is not None:
        s = jnp.where(mask[:, None, None, :], s, _MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1)
    if mask is not None:
        # A fully-masked query row softmaxes to uniform 1/Lk over _MASK_VALUE
        # scores; zero it so such rows are exactly 0 — the same convention as
        # ring_attention/flash_attention (denom-0 rows → 0). Partially-masked
        # rows are unaffected (their masked probs are already exactly 0).
        p = p * mask[:, None, None, :]
    return jnp.einsum(
        "bhlk,bkhd->blhd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
    ).astype(q.dtype)


def ring_attention(
    q,
    k,
    v,
    axis_name: str,
    mask=None,
    *,
    inner: str = "einsum",
    carry_dtype=None,
):
    """Exact attention with Q sharded and K/V streamed around ``axis_name``.

    Args:
      q, k, v: local shards ``[B, L_local, H, D]`` (global L = L_local * ring
        size; every device holds the same B).
      axis_name: bound mesh axis to ring over (e.g. ``"seq"``).
      mask: local key-padding mask ``[B, L_local]``, True = attend; rotates
        around the ring alongside K/V.
      inner: per-block compute. ``"einsum"`` materializes the local
        [L_local, L_local] score block (XLA-composed); ``"flash"`` runs the
        Pallas flash kernel per block (ops/flash_attention.py
        ``flash_attention_block``) and merges blocks by logsumexp — the
        O(L_local)-memory inner step for rings whose local score block
        would not fit.
      carry_dtype: dtype the K/V blocks ride the ring in. ``None`` (default)
        keeps the storage dtype: bf16 inputs hop in bf16 — half the ICI
        bytes of an f32 carry — at the cost of the BACKWARD rounding each
        hop's dK/dV cotangent to bf16 before the scan accumulates it, so
        gradient rounding grows ~O(sqrt(ring)) * 2^-8 relative (random-sign
        accumulation; pinned by tests/test_ring_attention.py at small
        rings). Rule of thumb: fine through ring <= 16; for longer rings —
        or bf16 training that proves grad-noise-sensitive — pass
        ``jnp.float32`` to carry (and accumulate) exactly, doubling SP
        traffic (docs/PERF.md SP table: ring bytes double, still matching
        Ulysses' bf16 bytes).

    Returns:
      ``[B, L_local, H, D]`` — this device's query shard attended over the
      *global* sequence, bit-comparable to :func:`dense_attention` on the
      gathered arrays (up to f32 reduction order).
    """
    if carry_dtype is not None:
        k = k.astype(carry_dtype)
        v = v.astype(carry_dtype)
    if inner == "flash":
        return _ring_attention_flash(q, k, v, axis_name, mask)
    if inner != "einsum":
        raise ValueError(f"unknown ring inner {inner!r}")
    n = lax.axis_size(axis_name)
    scale = q.shape[-1] ** -0.5
    b, l_q, h, d = q.shape

    q32 = q.astype(jnp.float32)
    o = jnp.zeros((b, l_q, h, d), jnp.float32)
    m = jnp.full((b, h, l_q), _MASK_VALUE, jnp.float32)
    denom = jnp.zeros((b, h, l_q), jnp.float32)

    def one_block(carry, _):
        k_blk, v_blk, mask_blk, o, m, denom = carry
        # Cast per block INSIDE the compute: the carry keeps storage dtype
        # (bf16), so every ppermute hop moves half the bytes an f32 carry
        # would — on a real ICI ring that halves SP communication. Forward
        # math is unchanged (same f32 casts, applied post-hop). Backward:
        # the astype VJP rounds each hop's dK/dV contribution to storage
        # dtype before the scan accumulates it, so bf16 inputs see O(ring)
        # accumulation rounding — the same contract as the flash inner
        # (whose carry always kept storage dtype); pinned with gradient
        # tolerance in tests/test_ring_attention.py::test_ring_bf16_inputs.
        s = (
            jnp.einsum(
                "blhd,bkhd->bhlk",
                q32,
                k_blk.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        if mask_blk is not None:
            s = jnp.where(mask_blk[:, None, None, :], s, _MASK_VALUE)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        if mask_blk is not None:
            # When every key so far is masked, m_new == _MASK_VALUE and
            # exp(s - m_new) == 1 for masked entries — zero them explicitly.
            p = p * mask_blk[:, None, None, :]
        corr = jnp.exp(m - m_new)
        denom = denom * corr + p.sum(axis=-1)
        o = o * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhlk,bkhd->blhd",
            p,
            v_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        # Stream the next block in: one ICI-neighbor hop, overlapped by XLA
        # with the block compute above (the whole point of the ring layout).
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        if mask_blk is not None:
            mask_blk = lax.ppermute(mask_blk, axis_name, perm)
        return (k_blk, v_blk, mask_blk, o, m_new, denom), None

    carry = (k, v, mask, o, m, denom)
    carry, _ = lax.scan(one_block, carry, None, length=n)
    _, _, _, o, m, denom = carry
    # A row with zero attendable keys ends with denom 0 — define output 0.
    safe = jnp.maximum(denom, 1e-37)
    return (o / safe.transpose(0, 2, 1)[..., None]).astype(q.dtype)


def _ring_attention_flash(q, k, v, axis_name: str, mask=None):
    """Ring outer loop over ICI, flash kernel inner loop over VMEM.

    Each ring step computes this query shard against the streamed K/V block
    with :func:`ops.flash_attention.flash_attention_block` (block-normalized
    output + per-row logsumexp), then merges blocks with the numerically
    stable weighted combine:  o = sum_j e^{lse_j - m} o_j / sum_j e^{lse_j - m}.
    Exact — same math as the einsum inner, different blocking.
    """
    from distributed_tensorflow_tpu.ops.flash_attention import (
        flash_attention_block,
    )

    n = lax.axis_size(axis_name)
    b, l_q, h, d = q.shape
    acc = jnp.zeros((b, l_q, h, d), jnp.float32)
    m = jnp.full((b, h, l_q), _MASK_VALUE, jnp.float32)
    z = jnp.zeros((b, h, l_q), jnp.float32)

    def one_block(carry, _):
        k_blk, v_blk, mask_blk, acc, m, z = carry
        # Cast to the query/storage dtype AT the kernel call: with an f32
        # carry_dtype the blocks ride (and their cotangents accumulate) in
        # f32, while the Pallas kernel still sees bf16 operands (f32 MXU
        # passes are ~8x slower — the r2 mistake; see flash_attention.py).
        o_j, lse_j = flash_attention_block(
            q, k_blk.astype(q.dtype), v_blk.astype(q.dtype), mask_blk
        )
        m_new = jnp.maximum(m, lse_j)
        w_old = jnp.exp(m - m_new)
        w_j = jnp.exp(lse_j - m_new)
        acc = (
            acc * w_old.transpose(0, 2, 1)[..., None]
            + o_j.astype(jnp.float32) * w_j.transpose(0, 2, 1)[..., None]
        )
        z = z * w_old + w_j
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        if mask_blk is not None:
            mask_blk = lax.ppermute(mask_blk, axis_name, perm)
        return (k_blk, v_blk, mask_blk, acc, m_new, z), None

    carry = (k, v, mask, acc, m, z)
    carry, _ = lax.scan(one_block, carry, None, length=n)
    _, _, _, acc, m, z = carry
    # Fully-masked rows: every o_j is 0, so acc is 0 regardless of z.
    safe = jnp.maximum(z, 1e-37)
    return (acc / safe.transpose(0, 2, 1)[..., None]).astype(q.dtype)
