"""All-to-all (Ulysses-style) sequence parallelism — the ring's alternative.

The task brief names both long-context strategies ("ring attention or
all-to-all sequence/context parallelism"); the framework ships both, same
contract, different data movement:

- **Ring** (parallel/ring_attention.py): Q stays put, K/V blocks stream
  around the ICI ring; compute is blockwise online-softmax. Communication
  is O(S) neighbor hops fully overlappable with block compute.
- **Ulysses** (this module): two `all_to_all`s re-partition the sharding
  from sequence to heads — each device then computes *full-sequence*
  attention for its `H/S` local heads with any single-device kernel (dense
  or the Pallas flash kernel), and a reverse exchange restores the
  sequence sharding. Communication is 2 all-to-alls of the activations;
  attention itself needs no modification at all.

Ulysses requires ``num_heads % ring_size == 0`` and holds full-L K/V for
its local heads (memory O(L * H/S) vs the ring's O(L_local * H)); the ring
has no head-count constraint. Both are exact.

Layout contract matches ring_attention: ``[B, L_local, H, D]`` shards with
the sequence dim over the bound axis; key-padding mask ``[B, L_local]``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _seq_to_heads(x, axis_name: str, s: int):
    """[B, L_loc, H, D] seq-sharded -> [B, L, H/S, D] head-sharded.

    Tiled all_to_all: my heads split into S groups (group i -> device i);
    the received L_loc chunks concatenate along the sequence in rank order,
    which is exactly the contiguous-slice seq sharding of the loaders.
    """
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)


def _heads_to_seq(x, axis_name: str, s: int):
    """[B, L, H/S, D] head-sharded -> [B, L_loc, H, D] seq-sharded (inverse)."""
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q, k, v, axis_name: str, mask=None, *, inner: str = "dense"
):
    """Exact attention over the global sequence via head re-partitioning.

    Args:
      q, k, v: local shards ``[B, L_local, H, D]``; ``H`` must divide by the
        axis size.
      axis_name: bound mesh axis carrying the sequence sharding.
      mask: local key-padding mask ``[B, L_local]`` (all-gathered once —
        bools are cheap relative to the activation exchanges).
      inner: the single-device attention applied per local head group:
        ``"dense"`` or ``"flash"`` (Pallas kernel — viable here because each
        device sees the full sequence, unlike the ring's streamed blocks).

    Returns:
      ``[B, L_local, H, D]`` — bit-comparable to
        ``ring_attention``/``dense_attention`` up to f32 reduction order.
    """
    s = lax.axis_size(axis_name)
    h = q.shape[2]
    if h % s:
        raise ValueError(f"num_heads {h} not divisible by axis size {s}; "
                         "use ring_attention for this geometry")
    qh = _seq_to_heads(q, axis_name, s)
    kh = _seq_to_heads(k, axis_name, s)
    vh = _seq_to_heads(v, axis_name, s)
    full_mask = None
    if mask is not None:
        full_mask = lax.all_gather(mask, axis_name, axis=1, tiled=True)
    if inner == "flash":
        from distributed_tensorflow_tpu.ops.flash_attention import flash_attention

        ctx = flash_attention(qh, kh, vh, mask=full_mask)
    elif inner == "dense":
        from distributed_tensorflow_tpu.parallel.ring_attention import (
            dense_attention,
        )

        ctx = dense_attention(qh, kh, vh, mask=full_mask)
    else:
        raise ValueError(f"unknown ulysses inner {inner!r}")
    return _heads_to_seq(ctx, axis_name, s)
