"""Mixture-of-experts with expert parallelism over an ``expert`` mesh axis.

The reference has no MoE (SURVEY.md §2 parallelism inventory: EP "absent");
this module completes the framework's parallelism set (dp/sp/tp/pp/ep)
the TPU-native way: experts are sharded over the ``expert`` axis (each
device owns ``n_experts / |axis|`` expert FFNs), tokens are routed
switch-style (top-1, capacity-bounded, load-balance aux loss), and each
shard computes ONLY its local experts' tokens — partial outputs psum over
the axis, so the engine's per-leaf sharded-param grad contract
(train/step.py: sharded leaves 1/t, replicated pmean) applies unchanged.

Routing is deterministic and identical on every shard (the router is
replicated), so there is no cross-shard token exchange to disagree about:
with tokens replicated across the expert axis each shard gathers its own
experts' tokens locally. (A token-sharded all-to-all dispatch layout is
the known next optimization for very large token counts; this layout keeps
routing exact and bandwidth-free on the batch.)

Capacity semantics are the standard Switch Transformer rules: each expert
processes at most ``capacity = ceil(capacity_factor * N / E)`` tokens, in
token order; overflow tokens are dropped (their output is 0 — pair MoE
blocks with residual connections, as transformers do).
"""

from __future__ import annotations

from collections.abc import Callable
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

# expert_fn(one_expert_params, tokens [C, H]) -> [C, H]
ExpertFn = Callable[[Any, jax.Array], jax.Array]


def switch_route(
    router_logits: jax.Array, capacity: int, valid: jax.Array | None = None
):
    """Top-1 routing with per-expert capacity (Switch Transformer).

    Args:
      router_logits: ``[N, E]`` (replicated across the expert axis).
      capacity: max tokens per expert.
      valid: optional ``[N]`` bool — tokens that actually exist (e.g. the
        attention mask of a padded batch). Invalid tokens are never kept,
        consume no capacity slots (so pads can't displace real tokens into
        the dropped-overflow path), and contribute nothing to the
        load-balance statistics.

    Returns:
      ``(assign [N], gate [N], slot [N], kept [N], aux)``: chosen expert,
      its softmax prob, the token's slot within the expert's capacity
      buffer (valid only where ``kept``), and the scalar load-balance aux
      loss (Shazeer/Fedus: E * sum_e f_e * p_e, over valid tokens).
    """
    n, e = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    assign = jnp.argmax(probs, axis=-1)
    gate = jnp.max(probs, axis=-1)
    onehot = jax.nn.one_hot(assign, e, dtype=jnp.float32)
    if valid is not None:
        onehot = onehot * valid[:, None].astype(jnp.float32)
    # Position of each token within its expert's queue (token order; invalid
    # tokens were zeroed out of onehot, so they occupy no position).
    pos = (jnp.cumsum(onehot, axis=0) * onehot).sum(-1)  # 1-based
    kept = (pos > 0) & (pos <= capacity)
    slot = (pos - 1).astype(jnp.int32)
    n_valid = onehot.sum() if valid is not None else jnp.float32(n)
    n_valid = jnp.maximum(n_valid, 1.0)
    frac_tokens = onehot.sum(axis=0) / n_valid
    if valid is not None:
        probs = probs * valid[:, None].astype(jnp.float32)
    frac_probs = probs.sum(axis=0) / n_valid
    aux = e * jnp.sum(frac_tokens * frac_probs)
    return assign, gate, slot, kept, aux


def moe_apply(
    expert_fn: ExpertFn,
    expert_params_local: Any,
    router_logits: jax.Array,
    x: jax.Array,
    *,
    axis_name: str | None = "expert",
    capacity_factor: float = 1.25,
    valid: jax.Array | None = None,
):
    """Apply a capacity-bounded top-1 MoE layer, experts sharded over
    ``axis_name``.

    Args:
      expert_fn: one expert's forward ``(params, [C, H]) -> [C, H]``.
      expert_params_local: this shard's slice of the stacked expert params —
        leading dim ``local_experts`` (shard_map in_spec ``P(axis_name, ...)``
        from the global ``[n_experts]`` stack; see
        :func:`expert_param_specs`). With ``axis_name=None`` the stack is
        the full expert set (single-shard reference semantics).
      router_logits: ``[N, E_global]`` routing scores (replicated across the
        expert axis; E_global = n_experts).
      x: tokens ``[N, H]``, replicated across the expert axis.
      capacity_factor: capacity = ceil(capacity_factor * N / E_global).
      valid: optional ``[N]`` bool of real (non-PAD) tokens; see
        :func:`switch_route`. Invalid tokens always emit 0.

    Returns:
      ``(y [N, H], aux)`` — gate-weighted expert outputs (0 for dropped
      tokens; add residually) and the load-balance aux loss scalar.
    """
    n, e_global = router_logits.shape
    local_e = jax.tree.leaves(expert_params_local)[0].shape[0]
    shards = 1 if axis_name is None else lax.axis_size(axis_name)
    if local_e * shards != e_global:
        raise ValueError(
            f"router has {e_global} experts but shards hold {local_e} x {shards}"
        )
    capacity = int(-(-capacity_factor * n // e_global))  # ceil
    assign, gate, slot, kept, aux = switch_route(router_logits, capacity, valid)
    first_local = (0 if axis_name is None else lax.axis_index(axis_name)) * local_e

    def one_expert(params_e, e_idx):
        mine = kept & (assign == e_idx)
        # Gather this expert's tokens into its capacity buffer. Unfilled
        # slots point at token 0 with weight 0 (w zeroes them out).
        token_idx = jnp.zeros((capacity,), jnp.int32)
        token_idx = token_idx.at[jnp.where(mine, slot, capacity)].set(
            jnp.arange(n, dtype=jnp.int32), mode="drop"
        )
        w = jnp.zeros((capacity,), x.dtype)
        w = w.at[jnp.where(mine, slot, capacity)].set(
            gate.astype(x.dtype), mode="drop"
        )
        out_c = expert_fn(params_e, x[token_idx]) * w[:, None]
        # Scatter back to token positions.
        y = jnp.zeros_like(x)
        return y.at[token_idx].add(out_c, mode="drop")

    def body(acc, scan_in):
        params_e, i = scan_in
        return acc + one_expert(params_e, first_local + i), None

    y, _ = lax.scan(
        body,
        jnp.zeros_like(x),
        (expert_params_local, jnp.arange(local_e)),
    )
    if axis_name is not None and shards > 1:
        y = lax.psum(y, axis_name)
    return y, aux


def stack_expert_params(per_expert_params: list) -> Any:
    """Stack per-expert param trees into one tree with leading [n_experts]."""
    from distributed_tensorflow_tpu.parallel.pipeline import stack_layer_params

    return stack_layer_params(per_expert_params)


def expert_param_specs(stacked_params, axis_name: str = "expert"):
    """Spec tree for a stacked expert set: leading dim over the expert axis."""
    from distributed_tensorflow_tpu.parallel.pipeline import pipeline_param_specs

    return pipeline_param_specs(stacked_params, axis_name)
