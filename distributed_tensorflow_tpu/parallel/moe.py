"""Mixture-of-experts with expert parallelism over an ``expert`` mesh axis.

The reference has no MoE (SURVEY.md §2 parallelism inventory: EP "absent");
this module completes the framework's parallelism set (dp/sp/tp/pp/ep)
the TPU-native way: experts are sharded over the ``expert`` axis (each
device owns ``n_experts / |axis|`` expert FFNs), tokens are routed
switch-style (top-1 default or GShard top-2 via ``topk=2``,
capacity-bounded, load-balance aux loss), and each
shard computes ONLY its local experts' tokens — partial outputs psum over
the axis, so the engine's per-leaf sharded-param grad contract
(train/step.py: sharded leaves 1/t, replicated pmean) applies unchanged.

Two dispatch layouts:

- :func:`moe_apply` — tokens replicated across the expert axis; every
  shard routes all tokens and computes only its experts', partial outputs
  psum. Exact global token-order capacity, zero dispatch traffic, N-fold
  redundant routing — right for small token counts.
- :func:`moe_apply_a2a` — token-sharded capacity-buffer all-to-all (the
  GShard/Switch production layout): each shard routes its N/S slice and
  only routed tokens travel. Grouped capacity semantics; bit-equivalent
  to the replicated layout when nothing overflows (pinned by test).

Capacity semantics are the standard Switch Transformer rules: each expert
processes at most ``capacity = ceil(capacity_factor * N / E)`` tokens, in
token order; overflow tokens are dropped (their output is 0 — pair MoE
blocks with residual connections, as transformers do).
"""

from __future__ import annotations

from collections.abc import Callable
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

# expert_fn(one_expert_params, tokens [C, H]) -> [C, H]
ExpertFn = Callable[[Any, jax.Array], jax.Array]


def switch_route(
    router_logits: jax.Array,
    capacity: int,
    valid: jax.Array | None = None,
    stats_axes: tuple[str, ...] = (),
):
    """Top-1 routing with per-expert capacity (Switch Transformer).

    Args:
      router_logits: ``[N, E]`` — this shard's tokens.
      capacity: max tokens per expert (per routing group — see
        :func:`moe_apply_a2a` for the grouped semantics).
      valid: optional ``[N]`` bool — tokens that actually exist (e.g. the
        attention mask of a padded batch). Invalid tokens are never kept,
        consume no capacity slots (so pads can't displace real tokens into
        the dropped-overflow path), and contribute nothing to the
        load-balance statistics.
      stats_axes: mesh axes to psum the load-balance statistics over, so
        the aux loss is the GLOBAL ratio when tokens are sharded (seq
        parallelism, token-sharded dispatch) — required by the engine's
        global-loss contract (train/step.py). Empty = local stats
        (replicated-token layouts, where local IS global).

    Returns:
      ``(assign [N], gate [N], slot [N], kept [N], aux)``: chosen expert,
      its softmax prob, the token's slot within the expert's capacity
      buffer (valid only where ``kept``), and the scalar load-balance aux
      loss (Shazeer/Fedus: E * sum_e f_e * p_e, over valid tokens).
    """
    n, e = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    assign = jnp.argmax(probs, axis=-1)
    gate = jnp.max(probs, axis=-1)
    onehot = jax.nn.one_hot(assign, e, dtype=jnp.float32)
    if valid is not None:
        onehot = onehot * valid[:, None].astype(jnp.float32)
    # Position of each token within its expert's queue (token order; invalid
    # tokens were zeroed out of onehot, so they occupy no position).
    pos = (jnp.cumsum(onehot, axis=0) * onehot).sum(-1)  # 1-based
    kept = (pos > 0) & (pos <= capacity)
    slot = (pos - 1).astype(jnp.int32)
    count_e = onehot.sum(axis=0)
    if valid is not None:
        probs = probs * valid[:, None].astype(jnp.float32)
    prob_e = probs.sum(axis=0)
    n_valid = count_e.sum() if valid is not None else jnp.float32(n)
    aux = _balance_aux(count_e, prob_e, n_valid, stats_axes, e)
    return assign, gate, slot, kept, aux


def _balance_aux(count_e, prob_e, n_valid, stats_axes, e):
    """Shazeer/Fedus load-balance aux from per-shard statistics, psum'd to
    GLOBAL ratios over every token-sharding axis (the engine's global-loss
    contract, train/step.py) — the single copy both routing fns share."""
    for ax in stats_axes:
        count_e = lax.psum(count_e, ax)
        prob_e = lax.psum(prob_e, ax)
        n_valid = lax.psum(n_valid, ax)
    n_valid = jnp.maximum(n_valid, 1.0)
    return e * jnp.sum((count_e / n_valid) * (prob_e / n_valid))


def switch_route_topk(
    router_logits: jax.Array,
    capacity: int,
    k: int,
    valid: jax.Array | None = None,
    stats_axes: tuple[str, ...] = (),
):
    """Top-k routing (k=2 is the GShard default) with per-expert capacity.

    Generalizes :func:`switch_route` (which stays the bit-exact top-1
    path): each token picks its k highest-prob experts with gates
    RENORMALIZED over the chosen k (g_j = p_j / sum_chosen p). Queue
    priority is by choice rank — every token's FIRST choice occupies
    expert queues before any second choice does (GShard's rule), then
    token order within a rank; per-expert ``capacity`` is unchanged, so
    top-2 doubles capacity pressure, which is the point of measuring it.
    Dropped choices contribute 0 (no gate renormalization after drops).

    Load-balance aux follows GShard: ``f_e`` counts FIRST choices only,
    ``p_e`` is the mean softmax mass, aux = E * sum_e f_e * p_e.

    Returns ``(assign [N,k], gate [N,k], slot [N,k], kept [N,k], aux)``.
    """
    n, e = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    top_p, assign = lax.top_k(probs, k)  # [N, k]
    gate = top_p / jnp.maximum(top_p.sum(-1, keepdims=True), 1e-9)
    v = (
        jnp.ones((n,), jnp.float32)
        if valid is None
        else valid.astype(jnp.float32)
    )
    onehot = jax.nn.one_hot(assign, e, dtype=jnp.float32) * v[:, None, None]
    # Queue positions: rank-major priority. offset[j] = total tokens all
    # earlier ranks placed in each expert's queue.
    offset = jnp.zeros((e,), jnp.float32)
    cols = []
    for j in range(k):
        oh = onehot[:, j, :]
        within = (jnp.cumsum(oh, axis=0) * oh).sum(-1)  # 1-based, 0 if none
        cols.append(within + (offset * oh).sum(-1) * (within > 0))
        offset = offset + oh.sum(axis=0)
    pos = jnp.stack(cols, axis=1)
    kept = (pos > 0) & (pos <= capacity)
    slot = (pos - 1).astype(jnp.int32)
    count_e = onehot[:, 0, :].sum(axis=0)  # first choices only (GShard)
    prob_e = (probs * v[:, None]).sum(axis=0)
    aux = _balance_aux(count_e, prob_e, v.sum(), stats_axes, e)
    return assign, gate, slot, kept, aux


def _route(router_logits, capacity, valid, stats_axes, topk):
    """Unified [N, k]-shaped routing: top-1 keeps the bit-exact
    :func:`switch_route` path (trajectory pins), top-k>=2 the GShard rules."""
    if topk == 1:
        assign, gate, slot, kept, aux = switch_route(
            router_logits, capacity, valid, stats_axes
        )
        return (
            assign[:, None],
            gate[:, None],
            slot[:, None],
            kept[:, None],
            aux,
        )
    return switch_route_topk(router_logits, capacity, topk, valid, stats_axes)


def moe_apply(
    expert_fn: ExpertFn,
    expert_params_local: Any,
    router_logits: jax.Array,
    x: jax.Array,
    *,
    axis_name: str | None = "expert",
    capacity_factor: float = 1.25,
    valid: jax.Array | None = None,
    stats_axes: tuple[str, ...] = (),
    topk: int = 1,
):
    """Apply a capacity-bounded MoE layer (top-1 default; ``topk=2`` = the
    GShard top-2 rules of :func:`switch_route_topk` — renormalized gates,
    per-expert capacity UNCHANGED so top-2 doubles capacity pressure;
    size ``capacity_factor`` accordingly), experts sharded over
    ``axis_name`` (tokens replicated across it; see :func:`moe_apply_a2a`
    for the token-sharded dispatch).

    Args:
      expert_fn: one expert's forward ``(params, [C, H]) -> [C, H]``.
      expert_params_local: this shard's slice of the stacked expert params —
        leading dim ``local_experts`` (shard_map in_spec ``P(axis_name, ...)``
        from the global ``[n_experts]`` stack; see
        :func:`expert_param_specs`). With ``axis_name=None`` the stack is
        the full expert set (single-shard reference semantics).
      router_logits: ``[N, E_global]`` routing scores (replicated across the
        expert axis; E_global = n_experts).
      x: tokens ``[N, H]``, replicated across the expert axis.
      capacity_factor: capacity = ceil(capacity_factor * N / E_global).
      valid: optional ``[N]`` bool of real (non-PAD) tokens; see
        :func:`switch_route`. Invalid tokens always emit 0.

    Returns:
      ``(y [N, H], aux)`` — gate-weighted expert outputs (0 for dropped
      tokens; add residually) and the load-balance aux loss scalar.
    """
    n, e_global = router_logits.shape
    local_e = jax.tree.leaves(expert_params_local)[0].shape[0]
    shards = 1 if axis_name is None else lax.axis_size(axis_name)
    if local_e * shards != e_global:
        raise ValueError(
            f"router has {e_global} experts but shards hold {local_e} x {shards}"
        )
    capacity = int(-(-capacity_factor * n // e_global))  # ceil
    assign, gate, slot, kept, aux = _route(
        router_logits, capacity, valid, stats_axes, topk
    )
    # Flattened (token, choice) entries: rank j of token i is entry i*k + j.
    # k=1 reduces to the original per-token arrays bit-for-bit.
    fa, fg = assign.reshape(-1), gate.reshape(-1)
    fs, fk = slot.reshape(-1), kept.reshape(-1)
    tok = jnp.repeat(jnp.arange(n, dtype=jnp.int32), assign.shape[1])
    first_local = (0 if axis_name is None else lax.axis_index(axis_name)) * local_e

    def one_expert(params_e, e_idx):
        mine = fk & (fa == e_idx)
        # Gather this expert's entries into its capacity buffer. Unfilled
        # slots point at token 0 with weight 0 (w zeroes them out).
        token_idx = jnp.zeros((capacity,), jnp.int32)
        token_idx = token_idx.at[jnp.where(mine, fs, capacity)].set(
            tok, mode="drop"
        )
        w = jnp.zeros((capacity,), x.dtype)
        w = w.at[jnp.where(mine, fs, capacity)].set(
            fg.astype(x.dtype), mode="drop"
        )
        out_c = expert_fn(params_e, x[token_idx]) * w[:, None]
        # Scatter back to token positions.
        y = jnp.zeros_like(x)
        return y.at[token_idx].add(out_c, mode="drop")

    def body(acc, scan_in):
        params_e, i = scan_in
        return acc + one_expert(params_e, first_local + i), None

    y, _ = lax.scan(
        body,
        jnp.zeros_like(x),
        (expert_params_local, jnp.arange(local_e)),
    )
    if axis_name is not None and shards > 1:
        y = lax.psum(y, axis_name)
    return y, aux


def moe_apply_a2a(
    expert_fn: ExpertFn,
    expert_params_local: Any,
    router_logits: jax.Array,
    x: jax.Array,
    *,
    axis_name: str = "expert",
    capacity_factor: float = 1.25,
    valid: jax.Array | None = None,
    stats_axes: tuple[str, ...] = (),
    tokens_sharded: bool = False,
    topk: int = 1,
):
    """Token-sharded MoE dispatch: capacity-buffer all-to-all over the
    expert axis (the GShard/Switch production layout — VERDICT r2 Weak #4).

    Same interface as :func:`moe_apply` (``x [N, H]`` replicated across the
    expert axis), different data movement: each shard routes only its
    contiguous ``N/S`` token slice, scatters kept tokens into per-expert
    capacity buffers ``[E, C, H]``, and ``lax.all_to_all`` delivers each
    expert shard exactly the tokens routed to its experts. Outputs ride the
    reverse all-to-all and an all-gather reassembles ``[N, H]``. Traffic
    scales with the routed capacity buffers (~2 x N/S x H per shard each
    way + the gather), not with S-fold replicated expert compute + a full
    ``[N, H]`` psum.

    Capacity semantics are GShard's *grouped* rule: each shard's token
    slice is a routing group with per-(group, expert) capacity
    ``ceil(capacity_factor * (N/S) / E)``. With no overflow this is
    bit-equivalent to the replicated dispatch (tests pin it); under
    overflow the drop pattern differs (per-group quotas instead of one
    global token-order queue) — the standard trade for scalable dispatch.

    ``stats_axes`` must include every axis tokens are sharded over
    (``axis_name`` at minimum, plus "seq" under sequence parallelism) so
    the load-balance aux is the global ratio on every shard.

    ``topk`` selects the routing fan-out exactly as in :func:`moe_apply`
    (2 = GShard top-2; per-expert capacity unchanged).

    ``tokens_sharded=True`` is the PRODUCTION layout (VERDICT r3 Missing
    #3): ``x``/``router_logits``/``valid`` are already this shard's slice
    (the batch itself is sharded over the expert axis — expert group ≡
    data group, the GShard arrangement), so there is no replicated non-MoE
    compute anywhere in the surrounding model, no entry slice, and no
    trailing all_gather — the return is the LOCAL ``[N_loc, H]`` output.
    Routing-group semantics are identical (each shard's slice is one
    group), so with matched groups it is bit-equivalent to the replicated
    entry (tests/test_bert_moe.py pins a whole trajectory). In this mode
    per-group aux statistics are the natural GShard choice — pass
    ``stats_axes=()`` (plus "seq" if sequence-sharded) and let the
    engine's DP-mean average the group auxes like any other loss term.
    """
    h = x.shape[-1]
    S = lax.axis_size(axis_name)
    local_e = jax.tree.leaves(expert_params_local)[0].shape[0]
    e_global = router_logits.shape[1]
    if local_e * S != e_global:
        raise ValueError(
            f"router has {e_global} experts but shards hold {local_e} x {S}"
        )
    if tokens_sharded:
        x_loc, logits_loc, valid_loc = x, router_logits, valid
        n_loc = x.shape[0]
    else:
        n = router_logits.shape[0]
        if n % S:
            raise ValueError(f"token count {n} not divisible by expert axis {S}")
        n_loc = n // S
        rank = lax.axis_index(axis_name)
        start = rank * n_loc
        x_loc = lax.dynamic_slice_in_dim(x, start, n_loc, 0)
        logits_loc = lax.dynamic_slice_in_dim(router_logits, start, n_loc, 0)
        valid_loc = (
            None
            if valid is None
            else lax.dynamic_slice_in_dim(valid, start, n_loc, 0)
        )
    capacity = int(-(-capacity_factor * n_loc // e_global))  # ceil, per group
    assign, gate, slot, kept, aux = _route(
        logits_loc, capacity, valid_loc, stats_axes, topk
    )

    # Scatter my kept (token, choice) entries into per-(global expert)
    # capacity buffers (k=1 reduces to the original per-token scatter).
    tokf = jnp.repeat(jnp.arange(n_loc, dtype=jnp.int32), assign.shape[1])
    idx_e = jnp.where(kept, assign, e_global).reshape(-1)  # overflow -> OOB
    idx_c = jnp.where(kept, slot, 0).reshape(-1)
    disp = jnp.zeros((e_global, capacity, h), x.dtype)
    disp = disp.at[idx_e, idx_c].set(x_loc[tokf], mode="drop")

    # A2A #1: block j of my buffers -> shard j. Received rows are ordered by
    # source shard: recv[j*local_e + k] = source j's buffer for my expert k.
    recv = lax.all_to_all(disp, axis_name, split_axis=0, concat_axis=0, tiled=True)
    toks = (
        recv.reshape(S, local_e, capacity, h)
        .transpose(1, 0, 2, 3)
        .reshape(local_e, S * capacity, h)
    )

    def body(_, scan_in):
        params_e, t = scan_in
        return None, expert_fn(params_e, t)

    _, outs = lax.scan(body, None, (expert_params_local, toks))

    # A2A #2 (reverse): give source j back its tokens' outputs. After the
    # inverse reshape, row j*local_e + k = outputs for source j from my
    # expert k; the exchange leaves [E, C, H] keyed by global expert id.
    back = (
        outs.reshape(local_e, S, capacity, h)
        .transpose(1, 0, 2, 3)
        .reshape(S * local_e, capacity, h)
    )
    ret = lax.all_to_all(back, axis_name, split_axis=0, concat_axis=0, tiled=True)

    # Per-choice output gather, gate-weighted and summed over the k choices.
    vals = ret[jnp.where(kept, assign, 0), jnp.where(kept, slot, 0)]  # [N,k,H]
    y_loc = (vals * (gate * kept).astype(x.dtype)[..., None]).sum(axis=1)
    if tokens_sharded:
        # Token-sharded contract: the caller's batch is sharded over the
        # expert axis, so the local outputs ARE the layer's outputs.
        return y_loc, aux
    # Reassemble the replicated [N, H] layout (rank-ordered slices).
    y = lax.all_gather(y_loc, axis_name, axis=0, tiled=True)
    return y, aux


def stack_expert_params(per_expert_params: list) -> Any:
    """Stack per-expert param trees into one tree with leading [n_experts]."""
    from distributed_tensorflow_tpu.parallel.pipeline import stack_layer_params

    return stack_layer_params(per_expert_params)


def expert_param_specs(stacked_params, axis_name: str = "expert"):
    """Spec tree for a stacked expert set: leading dim over the expert axis."""
    from distributed_tensorflow_tpu.parallel.pipeline import pipeline_param_specs

    return pipeline_param_specs(stacked_params, axis_name)
