"""Parallelism layer: mesh topology, collectives, DP flavors, sequence parallel.

This package is the TPU-native replacement for the reference's L1-L4 stack
(gRPC PS transport, cluster topology, placement policy, sync/async
optimization — SURVEY.md §1). Everything here is expressed as SPMD over a
``jax.sharding.Mesh`` with XLA collectives; there is no parameter server and
no per-role process launcher.
"""

from distributed_tensorflow_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    build_mesh,
    initialize_runtime,
)
from distributed_tensorflow_tpu.parallel import collectives  # noqa: F401
from distributed_tensorflow_tpu.parallel.pipeline import (  # noqa: F401
    pipeline_apply,
    pipeline_param_specs,
    stack_layer_params,
)
from distributed_tensorflow_tpu.parallel.moe import (  # noqa: F401
    expert_param_specs,
    moe_apply,
    moe_apply_a2a,
    stack_expert_params,
    switch_route,
    switch_route_topk,
)
from distributed_tensorflow_tpu.parallel.ring_attention import (  # noqa: F401
    dense_attention,
    ring_attention,
)
from distributed_tensorflow_tpu.parallel.ulysses import (  # noqa: F401
    ulysses_attention,
)
