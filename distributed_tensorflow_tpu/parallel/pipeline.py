"""GPipe-style pipeline parallelism as a compiled SPMD schedule.

The reference has no pipeline parallelism (SURVEY.md §2 parallelism
inventory: PP "absent"); this module is the TPU-native capability the
framework adds beyond parity, built the idiomatic way: the whole
microbatch schedule is ONE traced program — a ``lax.scan`` over ticks whose
stage hand-off is a ``lax.ppermute`` to the ICI neighbor — so ``jax.grad``
differentiates straight through it and the reverse pass IS the backward
pipeline (no hand-written schedule, no per-stage processes like GPipe's
original implementation).

Layout contract (mirrors the tensor-parallel contract in train/step.py):

- The layer stack's params carry a leading ``[n_layers]`` dim sharded over
  the ``pipeline`` mesh axis (spec ``P("pipeline", ...)``): stage ``s``
  holds layers ``[s*L/S, (s+1)*L/S)`` and scans them per tick.
- Inputs/outputs are replicated across the pipeline axis; the last stage's
  results are psum-broadcast so every stage returns the same output (the
  engine's per-leaf grad contract then applies: pipeline-sharded leaves
  scale 1/S, replicated leaves pmean — tests/test_pipeline.py pins
  equivalence with the sequential model).

Bubble fraction is the standard GPipe ``(S-1)/(M+S-1)``; pick
``n_microbatches >= 4*S`` for real runs.
"""

from __future__ import annotations

from collections.abc import Callable
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

# layer_fn(layer_params, activations) -> activations, applied per layer.
# With with_context=True the signature is layer_fn(layer_params, activations,
# ctx) where ctx = {"layer": global layer index, "microbatch": microbatch
# index} (both int32 scalars) — what a transformer block needs to slice its
# per-microbatch attention mask and fold a dropout rng uniquely per
# (layer, microbatch).
LayerFn = Callable[..., jax.Array]


def pipeline_apply(
    layer_fn: LayerFn,
    stacked_params: Any,
    x: jax.Array,
    *,
    axis_name: str = "pipeline",
    n_microbatches: int,
    with_context: bool = False,
    with_aux: bool = False,
):
    """Run a stage-sharded layer stack over ``x`` with GPipe microbatching.

    Args:
      layer_fn: one layer's forward, ``(params_of_one_layer, h) -> h`` with
        ``h`` shape-preserving (a transformer block, a residual MLP, ...).
      stacked_params: this stage's LOCAL slice of the stacked layer params —
        every leaf has leading dim ``local_layers`` (shard_map in_spec
        ``P(axis_name, ...)`` delivers it from the global ``[n_layers]``
        stack).
      x: the full local batch ``[B, ...]``, replicated across the pipeline
        axis. ``B`` must divide by ``n_microbatches``.
      axis_name: bound pipeline mesh axis.
      n_microbatches: GPipe M; higher M = smaller bubble, smaller per-tick
        matmuls.
      with_aux: layer_fn additionally returns a scalar auxiliary loss per
        (layer, microbatch) call — e.g. a MoE load-balance term. Drain- and
        fill-phase ticks compute garbage microbatches whose aux is MASKED
        OUT; valid contributions are summed across ticks and psum'd across
        stages, and the MEAN over the ``n_layers * M`` real calls is
        returned. (Each call's aux is a per-microbatch-group statistic —
        the grouped analog of the sequential encoder's per-layer full-batch
        aux; with capacity to spare and i.i.d. microbatches the two agree,
        and tests pin exact equality on tiled batches.)

    Returns:
      ``[B, ...]`` (with ``with_aux``: a ``(y, aux_mean)`` tuple) — the
      stack's output, identical on every stage.
    """
    S = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    M = n_microbatches
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by n_microbatches {M}")
    mb = x.reshape(M, B // M, *x.shape[1:])
    T = M + S - 1
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]

    n_local = jax.tree.leaves(stacked_params)[0].shape[0]

    def run_stage(h, mb_idx):
        def body(carry, xs):
            h, aux_acc = carry
            p_one, local_idx = xs
            args = (p_one, h)
            if with_context:
                ctx = {"layer": stage * n_local + local_idx, "microbatch": mb_idx}
                args = (p_one, h, ctx)
            out = layer_fn(*args)
            if with_aux:
                h, aux = out
                aux_acc = aux_acc + aux
            else:
                h = out
            return (h, aux_acc), None

        (h, aux_sum), _ = lax.scan(
            body, (h, jnp.float32(0.0)), (stacked_params, jnp.arange(n_local))
        )
        return h, aux_sum

    def tick(carry, t):
        buf, aux_acc = carry
        # Stage 0 ingests microbatch t (clamped in the drain phase — those
        # ticks compute garbage that is never collected); later stages take
        # the neighbor's value that arrived on the previous tick. Stage s
        # processes microbatch t - s on tick t (clamped the same way).
        inject = lax.dynamic_index_in_dim(
            mb, jnp.clip(t, 0, M - 1), 0, keepdims=False
        )
        h_in = jnp.where(stage == 0, inject, buf)
        mb_idx = jnp.clip(t - stage, 0, M - 1)
        h_out, aux_tick = run_stage(h_in, mb_idx)
        # Fill/drain ticks process clamped garbage — their aux must not
        # pollute the loss. Valid iff this stage holds a REAL microbatch.
        valid = ((t - stage) >= 0) & ((t - stage) < M)
        aux_acc = aux_acc + jnp.where(valid, aux_tick, 0.0)
        buf_next = lax.ppermute(h_out, axis_name, fwd_perm)
        return (buf_next, aux_acc), h_out

    buf0 = jnp.zeros_like(mb[0])
    (_, aux_acc), outs = lax.scan(
        tick, (buf0, jnp.float32(0.0)), jnp.arange(T)
    )
    # The last stage emits microbatch j at tick j + (S-1). Collect its M
    # valid outputs and broadcast them to every stage.
    outs_last = lax.dynamic_slice_in_dim(outs, S - 1, M, 0)
    y = lax.psum(
        jnp.where(stage == S - 1, outs_last, jnp.zeros_like(outs_last)),
        axis_name,
    )
    y = y.reshape(B, *x.shape[1:])
    if with_aux:
        # Sum over stages = sum over all n_layers * M real (layer, mb)
        # calls; normalize to the mean like the sequential encoder's
        # per-layer average.
        aux_mean = lax.psum(aux_acc, axis_name) / (n_local * S * M)
        return y, aux_mean
    return y


def stack_layer_params(per_layer_params: list) -> Any:
    """Stack per-layer param trees into one tree with leading [n_layers].

    The host-side companion of :func:`pipeline_apply`: turn
    ``[params_layer_0, ..., params_layer_{L-1}]`` (identical structures)
    into the stacked tree whose leaves get spec ``P("pipeline", ...)``.
    """
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_layer_params)


def pipeline_param_specs(stacked_params, axis_name: str = "pipeline"):
    """Spec tree for a stacked layer stack: leading dim over the pipeline axis."""
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(
        lambda leaf: P(axis_name, *(None,) * (leaf.ndim - 1)), stacked_params
    )
