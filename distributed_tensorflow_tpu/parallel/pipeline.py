"""GPipe-style pipeline parallelism as a compiled SPMD schedule.

The reference has no pipeline parallelism (SURVEY.md §2 parallelism
inventory: PP "absent"); this module is the TPU-native capability the
framework adds beyond parity, built the idiomatic way: the whole
microbatch schedule is ONE traced program — a ``lax.scan`` over ticks whose
stage hand-off is a ``lax.ppermute`` to the ICI neighbor — so ``jax.grad``
differentiates straight through it and the reverse pass IS the backward
pipeline (no hand-written schedule, no per-stage processes like GPipe's
original implementation).

Layout contract (mirrors the tensor-parallel contract in train/step.py):

- The layer stack's params carry a leading ``[n_layers]`` dim sharded over
  the ``pipeline`` mesh axis (spec ``P("pipeline", ...)``): stage ``s``
  holds layers ``[s*L/S, (s+1)*L/S)`` and scans them per tick.
- Inputs/outputs are replicated across the pipeline axis; the last stage's
  results are psum-broadcast so every stage returns the same output (the
  engine's per-leaf grad contract then applies: pipeline-sharded leaves
  scale 1/S, replicated leaves pmean — tests/test_pipeline.py pins
  equivalence with the sequential model).

Bubble fraction is the standard GPipe ``(S-1)/(M+S-1)``; pick
``n_microbatches >= 4*S`` for real runs.
"""

from __future__ import annotations

from collections.abc import Callable
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

_COLLECTIVE_PRIMS = frozenset({
    # jax._src.lax.parallel primitives, enumerated against jax 0.9.0.
    "psum", "pmax", "pmin", "ppermute", "pbroadcast", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "pgather",
    "psum_invariant", "ragged_all_to_all", "psend", "precv",
    "all_gather_invariant", "all_gather_reduced", "unreduced_psum",
    "unreduced_reduce_scatter",
})


def _jaxpr_has_collectives(jaxpr) -> bool:
    """True if any eqn (recursively, incl. scan/cond bodies) is a collective."""
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in _COLLECTIVE_PRIMS:
            return True
        for v in eqn.params.values():
            for sub in _subjaxprs(v):
                if _jaxpr_has_collectives(sub):
                    return True
    return False


def _subjaxprs(v):
    import jax.extend.core as jex_core

    if isinstance(v, jex_core.ClosedJaxpr):
        yield v.jaxpr
    elif isinstance(v, jex_core.Jaxpr):
        yield v
    elif isinstance(v, (list, tuple)):
        for item in v:
            yield from _subjaxprs(item)


def _layer_fn_has_collectives(layer_fn, stacked_params, h0, with_context) -> bool:
    """Trace one layer call — forward AND backward — and scan the jaxpr for
    collectives.

    Decides whether bubble masking is safe (see ``pipeline_apply``): a
    collective inside a branch that only part of the pipeline takes is
    undefined, so any hit forces the unconditional schedule. The backward
    must be traced too: ``pipeline_apply`` is differentiated through, and a
    ``custom_vjp`` layer op can be collective-free forward with a psum in
    its bwd rule. Conservative on a failed trace (collectives assumed).
    """
    p_one = jax.tree.map(lambda leaf: leaf[0], stacked_params)

    def probe(p, h):
        if with_context:
            ctx = {"layer": jnp.int32(0), "microbatch": jnp.int32(0)}
            fn = lambda p_, h_: layer_fn(p_, h_, ctx)  # noqa: E731
        else:
            fn = layer_fn
        out, vjp = jax.vjp(fn, p, h)
        return vjp(jax.tree.map(jnp.ones_like, out))

    try:
        jaxpr = jax.make_jaxpr(probe)(p_one, h0)
    except Exception:
        return True
    return _jaxpr_has_collectives(jaxpr.jaxpr)


# layer_fn(layer_params, activations) -> activations, applied per layer.
# With with_context=True the signature is layer_fn(layer_params, activations,
# ctx) where ctx = {"layer": global layer index, "microbatch": microbatch
# index} (both int32 scalars) — what a transformer block needs to slice its
# per-microbatch attention mask and fold a dropout rng uniquely per
# (layer, microbatch).
LayerFn = Callable[..., jax.Array]


def pipeline_apply(
    layer_fn: LayerFn,
    stacked_params: Any,
    x: jax.Array,
    *,
    axis_name: str = "pipeline",
    n_microbatches: int,
    with_context: bool = False,
    with_aux: bool = False,
    mask_bubble: bool | str = "auto",
):
    """Run a stage-sharded layer stack over ``x`` with GPipe microbatching.

    Args:
      layer_fn: one layer's forward, ``(params_of_one_layer, h) -> h`` with
        ``h`` shape-preserving (a transformer block, a residual MLP, ...).
      stacked_params: this stage's LOCAL slice of the stacked layer params —
        every leaf has leading dim ``local_layers`` (shard_map in_spec
        ``P(axis_name, ...)`` delivers it from the global ``[n_layers]``
        stack).
      x: the full local batch ``[B, ...]``, replicated across the pipeline
        axis. ``B`` must divide by ``n_microbatches``.
      axis_name: bound pipeline mesh axis.
      n_microbatches: GPipe M; higher M = smaller bubble, smaller per-tick
        matmuls.
      with_aux: layer_fn additionally returns a scalar auxiliary loss per
        (layer, microbatch) call — e.g. a MoE load-balance term. Drain- and
        fill-phase ticks compute garbage microbatches whose aux is MASKED
        OUT; valid contributions are summed across ticks and psum'd across
        stages, and the MEAN over the ``n_layers * M`` real calls is
        returned. (Each call's aux is a per-microbatch-group statistic —
        the grouped analog of the sequential encoder's per-layer full-batch
        aux; with capacity to spare and i.i.d. microbatches the two agree,
        and tests pin exact equality on tiled batches.)

    Returns:
      ``[B, ...]`` (with ``with_aux``: a ``(y, aux_mean)`` tuple) — the
      stack's output, identical on every stage.

    ``mask_bubble`` wraps each tick's stage compute in a ``lax.cond`` on
    tick validity so fill/drain ticks skip the layer math entirely instead
    of computing clamped garbage — ~(S-1)/(M+S-1) of each stage's tick work.
    The default ``"auto"`` enables it only when ``layer_fn`` contains no
    collectives: stages diverge on the branch at every fill/drain tick, and
    a sub-mesh collective inside the untaken branch is undefined —
    measured, not conjectured: a ``ppermute`` ring over a "seq" axis inside
    the cond silently corrupts its payload on the CPU mesh (the pair list
    spans devices that never execute the instruction), and a real pod could
    just as well deadlock. Grouped collectives (psum's disjoint
    replica_groups) happen to survive on CPU, but with no multi-chip
    hardware to prove it on, "auto" stays conservative: ANY collective in
    ``layer_fn`` keeps the unconditional schedule. Pass True/False to
    override (True with collectives is on you); scripts/pp_flops.py
    measures the executed-FLOP delta.
    """
    S = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    M = n_microbatches
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by n_microbatches {M}")
    mb = x.reshape(M, B // M, *x.shape[1:])
    T = M + S - 1
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]

    n_local = jax.tree.leaves(stacked_params)[0].shape[0]

    def run_stage(h, mb_idx):
        def body(carry, xs):
            h, aux_acc = carry
            p_one, local_idx = xs
            args = (p_one, h)
            if with_context:
                ctx = {"layer": stage * n_local + local_idx, "microbatch": mb_idx}
                args = (p_one, h, ctx)
            out = layer_fn(*args)
            if with_aux:
                h, aux = out
                aux_acc = aux_acc + aux
            else:
                h = out
            return (h, aux_acc), None

        (h, aux_sum), _ = lax.scan(
            body, (h, jnp.float32(0.0)), (stacked_params, jnp.arange(n_local))
        )
        return h, aux_sum

    if mask_bubble not in (True, False, "auto"):
        raise ValueError(
            f"mask_bubble must be True, False, or 'auto'; got {mask_bubble!r}"
        )
    if mask_bubble == "auto":
        mask_bubble = not _layer_fn_has_collectives(
            layer_fn, stacked_params, mb[0], with_context
        )

    def tick(carry, t):
        buf, aux_acc = carry
        # Stage 0 ingests microbatch t (clamped in the drain phase); later
        # stages take the neighbor's value that arrived on the previous
        # tick. Stage s processes microbatch t - s on tick t.
        inject = lax.dynamic_index_in_dim(
            mb, jnp.clip(t, 0, M - 1), 0, keepdims=False
        )
        h_in = jnp.where(stage == 0, inject, buf)
        mb_idx = jnp.clip(t - stage, 0, M - 1)
        # Valid iff this stage holds a REAL microbatch this tick. Fill ticks
        # (t < stage) and drain ticks (t - stage >= M) would otherwise run
        # the stage on clamped garbage that is never collected; gating the
        # whole stage in a lax.cond skips that compute at runtime. The
        # pass-through branch is exact: a buffer consumed at (s, t) always
        # came from a compute at (s-1, t-1), and valid(s-1, t-1) ==
        # valid(s, t), so no consumed value ever flows through the skip
        # branch (stage 0 reads `inject`, never the wrapped-around buf).
        valid = ((t - stage) >= 0) & ((t - stage) < M)
        if mask_bubble:
            h_out, aux_tick = lax.cond(
                valid,
                lambda h, i: run_stage(h, i),
                lambda h, i: (h, jnp.float32(0.0)),
                h_in,
                mb_idx,
            )
        else:
            h_out, aux_tick = run_stage(h_in, mb_idx)
            # Garbage ticks' aux must not pollute the loss.
            aux_tick = jnp.where(valid, aux_tick, 0.0)
        aux_acc = aux_acc + aux_tick
        buf_next = lax.ppermute(h_out, axis_name, fwd_perm)
        return (buf_next, aux_acc), h_out

    buf0 = jnp.zeros_like(mb[0])
    (_, aux_acc), outs = lax.scan(
        tick, (buf0, jnp.float32(0.0)), jnp.arange(T)
    )
    # The last stage emits microbatch j at tick j + (S-1). Collect its M
    # valid outputs and broadcast them to every stage.
    outs_last = lax.dynamic_slice_in_dim(outs, S - 1, M, 0)
    y = lax.psum(
        jnp.where(stage == S - 1, outs_last, jnp.zeros_like(outs_last)),
        axis_name,
    )
    y = y.reshape(B, *x.shape[1:])
    if with_aux:
        # Sum over stages = sum over all n_layers * M real (layer, mb)
        # calls; normalize to the mean like the sequential encoder's
        # per-layer average.
        aux_mean = lax.psum(aux_acc, axis_name) / (n_local * S * M)
        return y, aux_mean
    return y


def stack_layer_params(per_layer_params: list) -> Any:
    """Stack per-layer param trees into one tree with leading [n_layers].

    The host-side companion of :func:`pipeline_apply`: turn
    ``[params_layer_0, ..., params_layer_{L-1}]`` (identical structures)
    into the stacked tree whose leaves get spec ``P("pipeline", ...)``.
    """
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_layer_params)


def pipeline_param_specs(stacked_params, axis_name: str = "pipeline"):
    """Spec tree for a stacked layer stack: leading dim over the pipeline axis."""
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(
        lambda leaf: P(axis_name, *(None,) * (leaf.ndim - 1)), stacked_params
    )
