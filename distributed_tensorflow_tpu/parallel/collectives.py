"""First-class collective layer: XLA collectives over ICI/DCN.

This module is the data path that replaces BOTH reference transports
(SURVEY.md §2 native-component table):

- the gRPC PS round-trip (pull variables / push gradients to per-variable
  accumulators, SURVEY.md §3b hot loop) — gone entirely: parameters are
  resident on-device and gradients are averaged with one fused AllReduce;
- the NCCL ring allreduce (SURVEY.md §3d) — maps 1:1 to ``lax.psum`` over
  the mesh's ICI links.

All ``p*`` functions must run inside a context that binds the named axis —
i.e. under ``shard_map`` (or an equivalent SPMD region). Tree variants apply
leaf-wise over arbitrary pytrees (a whole gradient tree psums as one fused
collective after XLA's combiner pass).
"""

from __future__ import annotations

from collections.abc import Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AxisName = str | Sequence[str]


def psum_tree(tree, axis_name: AxisName):
    """Sum every leaf across ``axis_name``. One AllReduce per fused group."""
    return jax.tree.map(lambda x: lax.psum(x, axis_name), tree)


def pmean_tree(tree, axis_name: AxisName):
    """Average every leaf across ``axis_name``.

    This single call carries the full semantics of the reference's
    ``SyncReplicasOptimizer`` (SURVEY.md §3b): "no update until
    replicas_to_aggregate gradients arrive; gradients averaged; single global
    step" — under SPMD the barrier, the accumulators, and the chief token
    queue are all implied by the AllReduce itself.
    """
    return jax.tree.map(lambda x: lax.pmean(x, axis_name), tree)


def all_gather_tree(tree, axis_name: AxisName, axis: int = 0, tiled: bool = True):
    """All-gather every leaf along ``axis`` across the named mesh axis."""
    return jax.tree.map(
        lambda x: lax.all_gather(x, axis_name, axis=axis, tiled=tiled), tree
    )


def reduce_scatter_mean_tree(tree, axis_name: AxisName, axis: int = 0):
    """Reduce-scatter-mean: each shard ends with its slice of the mean.

    The building block for sharded-optimizer (ZeRO-style) updates: grads are
    reduce-scattered, the update runs on 1/N of the params, and params are
    all-gathered — strictly less HBM traffic than AllReduce+full update.
    """
    n = lax.psum(1, axis_name)
    return jax.tree.map(
        lambda x: lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)
        / n,
        tree,
    )


def ppermute_ring(x, axis_name: str, shift: int = 1):
    """Rotate shards around the mesh axis ring (neighbor sends over ICI).

    The primitive under ring attention and ring-based pipelining: on a TPU
    torus, ``ppermute`` to (i+1) % n is a pure neighbor transfer and overlaps
    with compute.
    """
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm=perm)


def axis_index(axis_name: AxisName):
    return lax.axis_index(axis_name)


def axis_size(axis_name: AxisName) -> int:
    return lax.axis_size(axis_name)


# ---------------------------------------------------------------------------
# Host-level placement helpers (outside shard_map): put pytrees on the mesh.
# ---------------------------------------------------------------------------


def replicate(tree, mesh: Mesh):
    """Replicate a host pytree onto every device of the mesh.

    The SPMD analog of variable placement onto parameter servers
    (``tf.train.replica_device_setter``, SURVEY.md §1 L3): instead of
    round-robining variables across ps hosts, every chip holds the full
    (or explicitly sharded) value and no remote read ever happens.
    """
    from distributed_tensorflow_tpu.parallel.mesh import replicated_sharding

    return jax.device_put(tree, replicated_sharding(mesh))  # one batched dispatch


def shard_batch(tree, mesh: Mesh, axes: Sequence[str] | None = None):
    """Shard a host batch along its leading dim over the DP mesh axes."""
    from distributed_tensorflow_tpu.parallel.mesh import batch_pspec

    if axes is None:
        spec = batch_pspec(mesh)
    else:
        spec = P(tuple(axes) if axes else None)
    return jax.device_put(tree, NamedSharding(mesh, spec))


def global_norm(tree) -> jax.Array:
    """L2 norm over a full pytree (for grad-norm logging / clipping)."""
    leaves = jax.tree.leaves(tree)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves)
    )
