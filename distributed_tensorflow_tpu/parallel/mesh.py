"""Device-mesh bootstrap: topology discovery for the single SPMD entrypoint.

Replaces the reference's cluster-topology and launcher layers (SURVEY.md §1
L2/L7, §3a-3b): where the reference declares ``tf.train.ClusterSpec({"ps":
[...], "worker": [...]})`` and spawns one gRPC ``tf.train.Server`` per role
via ``run_ps.py`` / ``run_worker.py``, here every host runs the *same*
program, calls :func:`initialize_runtime` once, and builds a
:class:`jax.sharding.Mesh` over all devices in the slice. Roles (ps/worker/
chief) do not exist; parameters live replicated or sharded on the TPUs
themselves, so the gRPC PS data path is eliminated by construction
(BASELINE.json:5 "zero gRPC PS traffic").

Mesh axis conventions used across the framework:

- ``"data"``  — data parallelism (batch sharded, params replicated).
- ``"model"`` — tensor/model parallelism (params sharded; optional).
- ``"seq"``   — sequence/context parallelism for long-context attention
  (ring attention over ICI neighbors; see ``parallel/ring_attention.py``).
- ``"replica"`` — reserved for a DCN axis across slices (multi-slice DP).

Within a slice, axes map onto ICI; across slices, put the outermost
(pure-DP) axis on DCN — this is the standard multislice recipe.
"""

from __future__ import annotations

import dataclasses
import logging
from collections.abc import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)

# Canonical axis names, in the order they should appear in a mesh (outermost
# first: slowest-varying ⇒ DCN/furthest devices, innermost ⇒ ICI neighbors).
AXIS_ORDER = ("replica", "data", "pipeline", "expert", "seq", "model")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape.

    ``axes`` maps axis name -> size. At most one axis may be ``-1``, meaning
    "all remaining devices". Axes of size 1 are kept (they are free and make
    ``PartitionSpec``s uniform across configs).

    Example::

        MeshSpec({"data": -1})                      # pure DP over everything
        MeshSpec({"data": -1, "seq": 4})            # DP x 4-way context parallel
        MeshSpec({"replica": 2, "data": -1})        # 2 slices over DCN
    """

    axes: Mapping[str, int]

    def __post_init__(self):
        unknown = [a for a in self.axes if a not in AXIS_ORDER]
        if unknown:
            raise ValueError(
                f"unknown mesh axes {unknown}; expected a subset of {AXIS_ORDER}"
            )
        wild = [a for a, n in self.axes.items() if n == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one axis may be -1, got {wild}")

    def resolve(self, n_devices: int) -> dict[str, int]:
        """Return concrete sizes in canonical axis order, filling the -1 axis."""
        fixed = 1
        for a, n in self.axes.items():
            if n != -1:
                if n <= 0:
                    raise ValueError(f"axis {a!r} must be positive or -1, got {n}")
                fixed *= n
        sizes = dict(self.axes)
        wild = [a for a, n in self.axes.items() if n == -1]
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wild[0]] = n_devices // fixed
        else:
            total = fixed
            if total != n_devices:
                raise ValueError(
                    f"mesh {dict(self.axes)} needs {total} devices, have {n_devices}"
                )
        return {a: sizes[a] for a in AXIS_ORDER if a in sizes}


_runtime_initialized = False


def initialize_runtime(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Initialize the multi-host JAX runtime (idempotent).

    This is the entire replacement for the reference's per-role server
    bootstrap (SURVEY.md §3a: ``tf.train.Server(cluster, "ps", k);
    server.join()``): on TPU pods the coordinator/process topology comes from
    the slice metadata automatically, so zero arguments are needed; explicit
    arguments are accepted for CPU/GPU multi-process testing.

    Must be called before anything touches the XLA backend (first ``jit`` /
    ``jax.devices()``), exactly like ``jax.distributed.initialize`` itself.
    With explicit arguments, failures propagate (a misconfigured cluster must
    not silently fall back to single-process). With no arguments, cluster
    auto-detection runs and single-host environments with no cluster metadata
    fall back to single-process mode.

    There is no ``server.join()`` analog because there are no passive
    processes — every host executes the compiled SPMD program.
    """
    global _runtime_initialized
    if _runtime_initialized:
        return
    explicit = coordinator_address is not None or num_processes is not None
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except Exception as e:
        if explicit or _cluster_env_present():
            # A declared or detected cluster that fails to initialize must
            # never silently degrade to N independent single-process jobs.
            raise
        logger.info("single-process runtime (no cluster metadata): %s", e)
    _runtime_initialized = True


def _cluster_env_present() -> bool:
    """True only for genuinely multi-host environment markers.

    Single-host TPU VMs (and tunneled dev environments) legitimately set
    ``TPU_WORKER_HOSTNAMES=localhost`` — a one-entry host list is not a
    cluster, and an init failure there must fall back to single-process.
    """
    import os

    env = os.environ.get
    if env("COORDINATOR_ADDRESS") or env("MEGASCALE_COORDINATOR_ADDRESS"):
        return True
    hostnames = env("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hostnames.split(",") if h.strip()]) > 1:
        return True
    try:
        # A nonzero worker id means this process is not the only worker even
        # if the launcher didn't propagate the full host list.
        if int(env("TPU_WORKER_ID", "0")) > 0:
            return True
    except ValueError:
        pass
    for count_var in ("SLURM_JOB_NUM_NODES", "OMPI_COMM_WORLD_SIZE"):
        try:
            if int(env(count_var, "0")) > 1:
                return True
        except ValueError:
            pass
    return False


def build_mesh(
    spec: MeshSpec | Mapping[str, int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a :class:`jax.sharding.Mesh` from slice metadata.

    Default is a 1-D ``"data"`` mesh over every addressable-or-not device in
    the job — the SPMD collapse of the reference's whole ps/worker cluster
    (SURVEY.md §1 "Key structural fact").

    Devices are ordered so that the innermost mesh axes land on
    ICI-contiguous neighbors (jax's default device order already follows the
    physical torus for TPU).
    """
    if spec is None:
        spec = MeshSpec({"data": -1})
    elif not isinstance(spec, MeshSpec):
        spec = MeshSpec(dict(spec))
    if devices is None:
        devices = jax.devices()
    sizes = spec.resolve(len(devices))
    names = tuple(sizes)
    shape = tuple(sizes.values())
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names=names)


def data_axes(mesh: Mesh) -> tuple[str, ...]:
    """Mesh axes over which the global batch is sharded (DP-like axes)."""
    return tuple(a for a in ("replica", "data") if a in mesh.axis_names)


@dataclasses.dataclass(frozen=True)
class ElasticPlan:
    """A dp/tp replan onto a surviving device set (see
    :func:`plan_elastic_mesh`). ``axes`` feeds straight into
    :func:`build_mesh` together with the surviving device list;
    ``notes`` records every fallback taken, in order."""

    axes: dict[str, int]
    n_devices: int      # devices the plan actually uses (dp * tp)
    dp: int
    tp: int
    grad_accum: int
    global_batch: int
    notes: tuple[str, ...] = ()


def plan_elastic_mesh(
    surviving: int | Sequence,
    *,
    tp: int = 1,
    global_batch: int = 0,
    grad_accum: int = 1,
    old_dp: int = 0,
) -> ElasticPlan:
    """Replan dp/tp onto the devices that survived a host loss.

    The elastic-resume recipe (docs/DEPLOY.md "Surviving a cluster"): when
    the :class:`~distributed_tensorflow_tpu.obs.fleet.FleetSupervisor`
    declares ``re_mesh``, the relaunch calls this with the surviving
    device set (or count), builds ``build_mesh(plan.axes, devices)``, and
    restores the sharded checkpoint straight into the new layout — orbax/
    tensorstore reshards on read, so no migration step exists.

    Degradation policy, mirroring ``serve.engine.plan_serve_mesh``: never
    refuse a survivable topology, always log what was given up —

    - ``tp`` that no longer divides the survivors falls back to its
      largest divisor that does (worst case 1 = pure DP; params restore
      into any tp width via the template machinery);
    - ``dp`` shrinks to the largest width dividing ``global_batch``
      (loaders require exact divisibility), idling the remainder — a
      smaller mesh that trains beats a bigger one that cannot;
    - ``grad_accum`` is rescaled by ``old_dp / new_dp`` (rounded up to a
      divisor of the per-device rows) so the GLOBAL batch — and with it
      the training trajectory's recipe — is preserved while the
      per-microslice device memory stays bounded at the old level.
    """
    n = surviving if isinstance(surviving, int) else len(surviving)
    if n < 1:
        raise ValueError(f"need at least one surviving device, got {n}")
    notes: list[str] = []
    tp = max(int(tp), 1)
    if tp > 1 and (tp > n or n % tp):
        new_tp = max(d for d in range(1, min(tp, n) + 1) if tp % d == 0 and n % d == 0)
        notes.append(
            f"tp={tp} does not divide {n} surviving devices; falling back "
            f"to tp={new_tp}"
        )
        tp = new_tp
    dp = n // tp
    if global_batch:
        if global_batch % dp:
            new_dp = max(d for d in range(1, dp + 1) if global_batch % d == 0)
            notes.append(
                f"global batch {global_batch} not divisible by dp={dp}; "
                f"shrinking to dp={new_dp} (idling {(dp - new_dp) * tp} "
                "surviving devices)"
            )
            dp = new_dp
    ga = max(int(grad_accum), 1)
    if old_dp and global_batch and old_dp != dp:
        # Preserve the old per-microslice device rows: the activation
        # memory the old layout was sized for.
        scaled = ga * old_dp / dp
        new_ga = max(int(-(-scaled // 1)), 1)  # ceil
        per_dev = global_batch // dp
        while per_dev % new_ga and new_ga < per_dev:
            new_ga += 1
        if new_ga != ga:
            notes.append(
                f"grad_accum {ga} -> {new_ga} (dp {old_dp} -> {dp}; global "
                f"batch {global_batch} preserved)"
            )
            ga = new_ga
    axes = {"data": dp}
    if tp > 1:
        axes["model"] = tp
    for note in notes:
        logger.warning("elastic re-mesh: %s", note)
    return ElasticPlan(
        axes=axes,
        n_devices=dp * tp,
        dp=dp,
        tp=tp,
        grad_accum=ga,
        global_batch=global_batch,
        notes=tuple(notes),
    )


@dataclasses.dataclass(frozen=True)
class DisaggPlan:
    """A prefill/decode role split over one device slice (see
    :func:`plan_disagg_mesh`). ``*_device_ids`` index into the caller's
    device list (``jax.devices()`` order); ``*_axes`` feed straight into
    :func:`build_mesh` together with the corresponding device subset.
    ``fell_back`` means the roles share devices (colocated) because the
    slice was too small to split; ``notes`` records every fallback taken,
    in order."""

    prefill_axes: dict[str, int]
    decode_axes: dict[str, int]
    prefill_device_ids: tuple[int, ...]
    decode_device_ids: tuple[int, ...]
    fell_back: bool = False
    notes: tuple[str, ...] = ()


def plan_disagg_mesh(
    n_devices: int,
    *,
    prefill_devices: int = -1,
    prefill_tp: int = 1,
    decode_tp: int = 1,
) -> DisaggPlan:
    """Plan a prefill/decode engine-role split onto one device slice.

    The serving twin of :func:`plan_elastic_mesh` and the inference rebirth
    of the reference's ps/worker role split (SURVEY.md §1 L2–L3): prefill
    is compute-bound and bursty, decode is memory-bound and steady, so a
    disaggregated fleet plans them onto disjoint device subsets of the same
    slice. Pure arithmetic — no jax import needed at plan time, so the
    shardcheck SC002 sweep can cross it with every layout.

    ``prefill_devices=-1`` means "half the slice, at least one device".
    Degradation policy mirrors ``plan_elastic_mesh``: never refuse a
    plannable topology, always note what was given up —

    - a slice too small to split (``n_devices < 2``) falls back to
      colocated roles sharing every device (``fell_back=True``);
    - an explicit ``prefill_devices`` that would leave the decode role
      empty is shrunk to leave at least one decode device;
    - a role ``tp`` that does not divide its device count falls back to
      the largest divisor that does (worst case 1).

    Genuinely invalid inputs (``n_devices < 1``, non-positive explicit
    ``prefill_devices``, non-positive tp) raise a clean ``ValueError`` —
    the plan-or-clean-ValueError contract the SC002 sweep enforces.
    """
    if n_devices < 1:
        raise ValueError(f"need at least one device, got {n_devices}")
    if prefill_devices != -1 and prefill_devices < 1:
        raise ValueError(
            f"prefill_devices must be -1 (auto) or >= 1, got {prefill_devices}"
        )
    if prefill_tp < 1 or decode_tp < 1:
        raise ValueError(
            f"role tp must be >= 1, got prefill_tp={prefill_tp} "
            f"decode_tp={decode_tp}"
        )
    notes: list[str] = []
    if n_devices < 2:
        notes.append(
            "slice too small to split roles; colocating prefill and decode "
            "on the same device"
        )
        ids = tuple(range(n_devices))
        pre_ids, dec_ids, fell_back = ids, ids, True
    else:
        n_pre = prefill_devices if prefill_devices != -1 else n_devices // 2
        if n_pre >= n_devices:
            notes.append(
                f"prefill_devices={n_pre} would leave no decode devices on "
                f"a {n_devices}-device slice; shrinking to {n_devices - 1}"
            )
            n_pre = n_devices - 1
        pre_ids = tuple(range(n_pre))
        dec_ids = tuple(range(n_pre, n_devices))
        fell_back = False

    def _role_axes(role: str, tp: int, n: int) -> dict[str, int]:
        if tp > 1 and (tp > n or n % tp):
            new_tp = max(
                d for d in range(1, min(tp, n) + 1) if tp % d == 0 and n % d == 0
            )
            notes.append(
                f"{role} tp={tp} does not divide its {n} devices; falling "
                f"back to tp={new_tp}"
            )
            tp = new_tp
        axes = {"data": n // tp}
        if tp > 1:
            axes["model"] = tp
        return axes

    prefill_axes = _role_axes("prefill", prefill_tp, len(pre_ids))
    decode_axes = _role_axes("decode", decode_tp, len(dec_ids))
    for note in notes:
        logger.warning("disagg role plan: %s", note)
    return DisaggPlan(
        prefill_axes=prefill_axes,
        decode_axes=decode_axes,
        prefill_device_ids=pre_ids,
        decode_device_ids=dec_ids,
        fell_back=fell_back,
        notes=tuple(notes),
    )


# Short axis tags for layout labels, keyed by the canonical axis names.
_AXIS_SHORT = {
    "replica": "rep",
    "data": "dp",
    "pipeline": "pp",
    "expert": "ep",
    "seq": "sp",
    "model": "tp",
}


def layout_label(mesh: Mesh) -> str:
    """Compact human/metric-label tag for a mesh layout.

    Size-1 axes are dropped (they change no sharding): ``{"data": 2,
    "model": 4}`` -> ``"dp2-tp4"``; a single-device mesh -> ``"single"``.
    Used as the serving engines' layout identity — it keys the
    layout-labelled ServeMetrics instruments and the serve_bench per-layout
    report, so it must be stable across runs (it is: axis order is the
    mesh's, which ``build_mesh`` derives from ``AXIS_ORDER``).
    """
    parts = [
        f"{_AXIS_SHORT.get(a, a)}{mesh.shape[a]}"
        for a in mesh.axis_names
        if mesh.shape[a] > 1
    ]
    return "-".join(parts) or "single"


def batch_pspec(mesh: Mesh) -> P:
    """The canonical batch PartitionSpec: leading dim over the DP axes.

    Single source of truth for the DP-batch rule — used by the data loader,
    the train/eval steps, and ``batch_sharding``.
    """
    axes = data_axes(mesh)
    return P(axes if axes else None)


def batch_sharding(mesh: Mesh, ndim: int = 0) -> NamedSharding:
    """Sharding for a batch: leading dim split over the DP axes, rest replicated.

    ``ndim`` is accepted for readability at call sites but unused:
    PartitionSpec only needs the leading entry.
    """
    del ndim
    return NamedSharding(mesh, batch_pspec(mesh))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (the SPMD analog of PS-hosted variables —
    except every chip holds a copy and no RecvTensor RPC exists,
    SURVEY.md §2 native-component table row 1)."""
    return NamedSharding(mesh, P())


def local_batch_size(
    global_batch: int, mesh: Mesh, extra_axes: Sequence[str] = ()
) -> int:
    """Per-host slice of the global batch (for building host-local arrays).

    The single rule every loader follows (data/loader.py, data/text.py):
    each of the job's ``jax.process_count()`` hosts materializes an equal
    contiguous slice; ``jax.make_array_from_process_local_data`` assembles
    the global array. Validates divisibility by both the DP world size
    (shard shapes must be static) and the host count. ``extra_axes`` names
    additional mesh axes the batch rows shard over (e.g. ``("expert",)``
    under the token-sharded MoE layout) so the loud divisibility check
    covers the full row partition, not just the DP axes.
    """
    axes = tuple(data_axes(mesh)) + tuple(
        a for a in extra_axes if a in mesh.axis_names
    )
    n_data = int(np.prod([mesh.shape[a] for a in axes], initial=1))
    if global_batch % n_data:
        raise ValueError(f"global batch {global_batch} not divisible by {n_data}")
    n_proc = jax.process_count()
    if global_batch % n_proc:
        raise ValueError(f"global batch {global_batch} not divisible by {n_proc} hosts")
    if n_proc > 1:
        # The equal-slice-per-process rule assumes every process owns the
        # same number of mesh devices (true on uniform TPU slices). On a
        # job where hosts own unequal shares, each host's slice would no
        # longer match its addressable shards and
        # make_array_from_process_local_data would mis-assemble — fail
        # loudly instead of corrupting batches.
        counts: dict[int, int] = {}
        for d in mesh.devices.flat:
            counts[d.process_index] = counts.get(d.process_index, 0) + 1
        if len(counts) != n_proc or len(set(counts.values())) > 1:
            # len(counts) < n_proc: a process owns ZERO mesh devices but
            # would still be assigned a batch slice — just as mis-assembled
            # as an uneven split.
            raise ValueError(
                "mesh devices are unevenly distributed across processes "
                f"({counts} over {n_proc} processes); equal per-process "
                "batch slices require uniform local device counts"
            )
    return global_batch // n_proc
