"""Seeded synthetic datasets with learnable class structure.

Stand-ins for MNIST/CIFAR/ImageNet in a zero-egress environment: each class
gets a fixed random template; samples are template + noise, so a real model
trained on them converges (loss falls, accuracy rises) — which is what the
reference's own validation strategy ("run it and watch the loss",
SURVEY.md §4) needs from its data.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class SyntheticClassification:
    """In-memory synthetic image-classification dataset."""

    images: np.ndarray  # [N,H,W,C] float32
    labels: np.ndarray  # [N] int32

    def __len__(self):
        return len(self.labels)


def synthetic_image_classification(
    num_examples: int,
    image_shape: tuple[int, int, int],
    num_classes: int,
    *,
    seed: int = 0,
    noise: float = 0.5,
) -> SyntheticClassification:
    """Class-template images + Gaussian noise; linearly separable-ish."""
    rng = np.random.default_rng(seed)
    templates = rng.normal(size=(num_classes, *image_shape)).astype(np.float32)
    labels = rng.integers(0, num_classes, size=num_examples).astype(np.int32)
    images = templates[labels] + noise * rng.normal(
        size=(num_examples, *image_shape)
    ).astype(np.float32)
    return SyntheticClassification(images=images.astype(np.float32), labels=labels)
