"""ctypes bindings for the native (C++) input pipeline.

The reference rides tf.data's C++ threadpool for its input pipelines
(SURVEY.md §2); this is the rebuild's own native layer: a pthread worker
pool in ``native/data_pipeline.cpp`` that shuffles, augments (pad-crop /
flip / per-image standardization), and stages batches in a bounded ring —
deterministic by construction (per-ticket RNG, in-order staging), unlike the
reference's racy async readers.

``NativePipeline`` builds the shared library on first use (g++ is in the
image); if the toolchain is unavailable the caller falls back to the numpy
path (``native_available()`` gates it).
"""

from __future__ import annotations

import ctypes
import logging
import subprocess
from pathlib import Path

import numpy as np

logger = logging.getLogger(__name__)

_NATIVE_DIR = Path(__file__).resolve().parents[2] / "native"
_LIB_PATH = _NATIVE_DIR / "libdata_pipeline.so"
_lib = None
_build_failed = False


def _load() -> ctypes.CDLL | None:
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    if not _LIB_PATH.exists():
        try:
            subprocess.run(
                ["make", "-C", str(_NATIVE_DIR)],
                check=True,
                capture_output=True,
                text=True,
            )
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            logger.warning("native pipeline build failed, using numpy path: %s", e)
            _build_failed = True
            return None
    lib = ctypes.CDLL(str(_LIB_PATH))
    lib.dp_create.restype = ctypes.c_void_p
    lib.dp_create.argtypes = [
        ctypes.c_void_p,  # images
        ctypes.c_void_p,  # labels
        ctypes.c_int64,   # n
        ctypes.c_int, ctypes.c_int, ctypes.c_int,  # h, w, c
        ctypes.c_int,     # batch
        ctypes.c_int, ctypes.c_int, ctypes.c_int,  # pad, flip, standardize
        ctypes.c_uint64,  # seed
        ctypes.c_int, ctypes.c_int,  # n_threads, queue_cap
    ]
    lib.dp_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
    lib.dp_destroy.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


def native_available() -> bool:
    return _load() is not None


class NativePipeline:
    """Threaded batch producer over an in-memory dataset.

    Yields ``(images [B,H,W,C] f32, labels [B] i32)`` numpy batches with
    augmentation done by the C++ worker pool. Deterministic for a fixed
    ``seed`` independent of ``n_threads``.
    """

    def __init__(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        batch: int,
        *,
        pad: int = 0,
        flip: bool = False,
        standardize: bool = False,
        seed: int = 0,
        n_threads: int = 4,
        queue_cap: int = 8,
    ):
        lib = _load()
        if lib is None:
            raise RuntimeError("native pipeline library unavailable")
        # Own contiguous copies: the C++ side keeps raw pointers to these.
        self._images = np.ascontiguousarray(images, np.float32)
        self._labels = np.ascontiguousarray(labels, np.int32)
        n, h, w, c = self._images.shape
        self._shape = (batch, h, w, c)
        self._batch = batch
        self._lib = lib
        self._handle = lib.dp_create(
            self._images.ctypes.data_as(ctypes.c_void_p),
            self._labels.ctypes.data_as(ctypes.c_void_p),
            n, h, w, c, batch,
            pad, int(flip), int(standardize),
            seed, n_threads, queue_cap,
        )

    def next(self) -> tuple[np.ndarray, np.ndarray]:
        out_images = np.empty(self._shape, np.float32)
        out_labels = np.empty((self._batch,), np.int32)
        self._lib.dp_next(
            self._handle,
            out_images.ctypes.data_as(ctypes.c_void_p),
            out_labels.ctypes.data_as(ctypes.c_void_p),
        )
        return out_images, out_labels

    def __iter__(self):
        while True:
            yield self.next()

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.dp_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
