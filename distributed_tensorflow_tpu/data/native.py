"""ctypes bindings for the native (C++) input pipeline.

The reference rides tf.data's C++ threadpool for its input pipelines
(SURVEY.md §2); this is the rebuild's own native layer: a pthread worker
pool in ``native/data_pipeline.cpp`` that samples a per-epoch permutation
(without replacement, via an O(1) Feistel index permutation), augments
(pad-crop / flip / per-image standardization for CIFAR; random-resized-crop
+ per-channel normalization for ImageNet), and stages batches in a bounded
ring — deterministic by construction (per-ticket RNG, in-order staging),
unlike the reference's racy async readers.

``NativePipeline`` builds the shared library on first use (g++ is in the
image); if the toolchain is unavailable the caller falls back to the numpy
path (``native_available()`` gates it).
"""

from __future__ import annotations

import ctypes
import logging
import subprocess
import threading
from pathlib import Path

import numpy as np

logger = logging.getLogger(__name__)

_NATIVE_DIR = Path(__file__).resolve().parents[2] / "native"
_LIB_PATH = _NATIVE_DIR / "libdata_pipeline.so"
# _load() is reached both from the main thread (native_available probes)
# and from prefetch feeder threads first touching a NativePipeline; the
# lock keeps the lazy check-then-build-then-publish atomic so two threads
# can never race concurrent `make -B` builds of the same .so.
_LOAD_LOCK = threading.Lock()
_lib = None
_build_failed = False


def _load() -> ctypes.CDLL | None:
    global _lib, _build_failed
    with _LOAD_LOCK:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        src = _NATIVE_DIR / "data_pipeline.cpp"
        if not _LIB_PATH.exists() or (
            src.exists() and src.stat().st_mtime > _LIB_PATH.stat().st_mtime
        ):
            try:
                subprocess.run(
                    ["make", "-C", str(_NATIVE_DIR), "-B"],
                    check=True,
                    capture_output=True,
                    text=True,
                )
            except (subprocess.CalledProcessError, FileNotFoundError) as e:
                logger.warning(
                    "native pipeline build failed, using numpy path: %s", e
                )
                _build_failed = True
                return None
        return _bind(ctypes.CDLL(str(_LIB_PATH)))


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.dp_create.restype = ctypes.c_void_p
    lib.dp_create.argtypes = [
        ctypes.c_void_p,  # images
        ctypes.c_void_p,  # labels
        ctypes.c_int64,   # n
        ctypes.c_int, ctypes.c_int, ctypes.c_int,  # h, w, c
        ctypes.c_int, ctypes.c_int,  # out_h, out_w
        ctypes.c_int,     # batch
        ctypes.c_int, ctypes.c_int, ctypes.c_int,  # pad, flip, standardize
        ctypes.c_int, ctypes.c_float,  # rrc, rrc_min_area
        ctypes.c_int,     # src_u8
        ctypes.c_void_p, ctypes.c_void_p,  # mean, stddev
        ctypes.c_uint64,  # seed
        ctypes.c_uint64, ctypes.c_uint64,  # stream_offset, stream_stride
        ctypes.c_uint64,  # start_ticket
        ctypes.c_int, ctypes.c_int,  # n_threads, queue_cap
    ]
    lib.dp_next.restype = ctypes.c_int
    lib.dp_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
    lib.dp_destroy.argtypes = [ctypes.c_void_p]
    global _lib
    _lib = lib
    return _lib


def native_available() -> bool:
    return _load() is not None


def resolve_input_dtype(name) -> np.dtype:
    """Normalize an input-batch dtype knob to a numpy dtype.

    ``bfloat16`` resolves through ``ml_dtypes`` (numpy has no native
    bf16); only float32 and bfloat16 are supported — images narrower
    than bf16 lose augmentation precision for no transfer win the
    roofline credits.
    """
    s = str(name).lower()
    if s in ("bfloat16", "bf16"):
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    if s in ("float32", "f32", "fp32"):
        return np.dtype(np.float32)
    raise ValueError(
        f"input dtype {name!r} not supported: pick float32 or bfloat16"
    )


class NativePipeline:
    """Threaded batch producer over an in-memory (or memory-mapped) dataset.

    Yields ``(images [B,out_H,out_W,C] f32, labels [B] i32)`` numpy batches
    with augmentation done by the C++ worker pool. Deterministic for a fixed
    ``seed`` independent of ``n_threads``. Sampling is per-epoch permutation
    without replacement; ``start_ticket`` resumes the stream at batch N
    (checkpoint-resume without replaying data).

    ``images`` may be float32 or uint8 (uint8 pixels are scaled by 1/255 —
    pass an np.memmap for datasets that don't fit RAM). When
    ``out_size != (H, W)`` or ``rrc=True``, images are (random-resized-)
    cropped and bilinearly resampled to ``out_size``.

    Multi-host: pass ``stream_offset = host_index * batch`` and
    ``stream_stride = num_hosts * batch`` with the SAME seed everywhere —
    all hosts then share each epoch's permutation and read disjoint slices
    (the explicit form of tf.data's ``shard(num_hosts, host_id)``).

    The C++ pool overlaps *augmentation* with Python; ``next()`` still
    copies the staged batch out and the caller still pays the
    host→device transfer. Wrapping the consuming stream in
    ``data.prefetch`` moves both off the step stream — the two queues
    compose (C++ ring feeds the Python feeder thread). ``close()`` (or
    exiting the ``with`` block) unblocks any thread waiting in ``next()``,
    which then raises instead of returning garbage.

    ``out_dtype="bfloat16"`` converts batches at the Python copy-out
    (the C++ ring itself stays float32 — augmentation arithmetic keeps
    full precision; only the staged result narrows). Halving the batch
    bytes halves the host→device transfer the roofline charges to input
    (docs/PERF.md r19) and matmul inputs arrive in the accelerator's
    native compute dtype.
    """

    def __init__(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        batch: int,
        *,
        out_size: tuple[int, int] | None = None,
        pad: int = 0,
        flip: bool = False,
        standardize: bool = False,
        rrc: bool = False,
        rrc_min_area: float = 0.08,
        mean: np.ndarray | None = None,
        stddev: np.ndarray | None = None,
        seed: int = 0,
        stream_offset: int = 0,
        stream_stride: int = 0,
        start_ticket: int = 0,
        n_threads: int = 4,
        queue_cap: int = 8,
        out_dtype: str = "float32",
    ):
        self._out_dtype = resolve_input_dtype(out_dtype)
        lib = _load()
        if lib is None:
            raise RuntimeError("native pipeline library unavailable")
        # Own contiguous arrays: the C++ side keeps raw pointers to these.
        # uint8 sources stay uint8 (4x smaller; memmaps pass through without
        # materializing), anything else becomes float32.
        if images.dtype == np.uint8:
            self._images = images if images.flags["C_CONTIGUOUS"] else np.ascontiguousarray(images)
            src_u8 = 1
        else:
            self._images = np.ascontiguousarray(images, np.float32)
            src_u8 = 0
        self._labels = np.ascontiguousarray(labels, np.int32)
        n, h, w, c = self._images.shape
        oh, ow = out_size if out_size is not None else (h, w)
        self._shape = (batch, oh, ow, c)
        self._batch = batch
        self._lib = lib
        self._mean = (
            np.ascontiguousarray(mean, np.float32) if mean is not None else None
        )
        self._std = (
            np.ascontiguousarray(stddev, np.float32) if stddev is not None else None
        )
        if (self._mean is None) != (self._std is None):
            raise ValueError("mean and stddev must be given together")
        self._handle = lib.dp_create(
            self._images.ctypes.data_as(ctypes.c_void_p),
            self._labels.ctypes.data_as(ctypes.c_void_p),
            n, h, w, c, oh, ow, batch,
            pad, int(flip), int(standardize),
            int(rrc), float(rrc_min_area), src_u8,
            self._mean.ctypes.data_as(ctypes.c_void_p) if self._mean is not None else None,
            self._std.ctypes.data_as(ctypes.c_void_p) if self._std is not None else None,
            seed, stream_offset, stream_stride, start_ticket,
            n_threads, queue_cap,
        )

    def next(self) -> tuple[np.ndarray, np.ndarray]:
        if self._handle is None:
            raise RuntimeError("pipeline is closed")
        out_images = np.empty(self._shape, np.float32)
        out_labels = np.empty((self._batch,), np.int32)
        ok = self._lib.dp_next(
            self._handle,
            out_images.ctypes.data_as(ctypes.c_void_p),
            out_labels.ctypes.data_as(ctypes.c_void_p),
        )
        if not ok:
            # Racing close()/destruction: never hand back uninitialized
            # buffers as if they were data.
            raise RuntimeError("pipeline stopped while waiting for a batch")
        if self._out_dtype != np.float32:
            out_images = out_images.astype(self._out_dtype)
        return out_images, out_labels

    def __iter__(self):
        while True:
            yield self.next()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.dp_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
