"""Real-dataset readers, gated on local file presence (zero-egress env).

Parity with the reference's per-workload input pipelines (SURVEY.md §2
"Input pipelines" row): MNIST idx files and CIFAR-10 python-pickle batches
load into the same in-memory :class:`SyntheticClassification` container the
synthetic generators produce, so every downstream component (loader,
train step, CLI) is agnostic to where the pixels came from.

``load_dataset`` is the single entry: real data when the files exist under
``data_dir``, seeded synthetic otherwise — the run never fails for lack of
a download.
"""

from __future__ import annotations

import gzip
import logging
import pickle
import re
import struct
from pathlib import Path

import numpy as np

logger = logging.getLogger(__name__)

from distributed_tensorflow_tpu.data.synthetic import (
    SyntheticClassification,
    synthetic_image_classification,
)

_MNIST_IMAGE_MAGIC = 2051
_MNIST_LABEL_MAGIC = 2049


def _open_maybe_gz(path: Path):
    if path.suffix == ".gz":
        return gzip.open(path, "rb")
    return path.open("rb")


def _read_idx_images(path: Path) -> np.ndarray:
    with _open_maybe_gz(path) as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != _MNIST_IMAGE_MAGIC:
            raise ValueError(f"{path}: bad idx image magic {magic}")
        data = np.frombuffer(f.read(n * rows * cols), np.uint8)
    return data.reshape(n, rows, cols, 1)


def _read_idx_labels(path: Path) -> np.ndarray:
    with _open_maybe_gz(path) as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != _MNIST_LABEL_MAGIC:
            raise ValueError(f"{path}: bad idx label magic {magic}")
        return np.frombuffer(f.read(n), np.uint8).astype(np.int32)


def _find(data_dir: Path, names: list[str]) -> Path | None:
    for name in names:
        for cand in (data_dir / name, data_dir / (name + ".gz")):
            if cand.exists():
                return cand
    return None


def load_mnist(data_dir: str | Path, split: str = "train") -> SyntheticClassification:
    """MNIST from idx files (optionally .gz). Pixels scaled to [0, 1]."""
    data_dir = Path(data_dir)
    prefix = "train" if split == "train" else "t10k"
    img = _find(data_dir, [f"{prefix}-images-idx3-ubyte", f"{prefix}-images.idx3-ubyte"])
    lab = _find(data_dir, [f"{prefix}-labels-idx1-ubyte", f"{prefix}-labels.idx1-ubyte"])
    if img is None or lab is None:
        raise FileNotFoundError(f"no MNIST {split} idx files under {data_dir}")
    images = _read_idx_images(img).astype(np.float32) / 255.0
    labels = _read_idx_labels(lab)
    if len(images) != len(labels):
        raise ValueError(f"{len(images)} images vs {len(labels)} labels")
    return SyntheticClassification(images=images, labels=labels)


def load_cifar10(
    data_dir: str | Path, split: str = "train"
) -> SyntheticClassification:
    """CIFAR-10 from the python-version pickle batches. NHWC [0, 1] float."""
    data_dir = Path(data_dir)
    base = data_dir / "cifar-10-batches-py"
    if not base.exists():
        base = data_dir
    names = (
        [f"data_batch_{i}" for i in range(1, 6)] if split == "train" else ["test_batch"]
    )
    images, labels = [], []
    for name in names:
        path = base / name
        if not path.exists():
            raise FileNotFoundError(f"missing CIFAR-10 batch {path}")
        with path.open("rb") as f:
            d = pickle.load(f, encoding="bytes")
        raw = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        images.append(raw.astype(np.float32) / 255.0)
        labels.append(np.asarray(d[b"labels"], np.int32))
    return SyntheticClassification(
        images=np.concatenate(images), labels=np.concatenate(labels)
    )


# ImageNet channel statistics (RGB, [0,1] pixel scale) — the standard
# normalization constants for ImageNet-trained CNNs.
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def _decode_resize_center(img, size: int) -> np.ndarray:
    """PIL image -> RGB u8 [size, size, 3]: shorter side to ``size``, center crop."""
    from PIL import Image

    img = img.convert("RGB")
    w, h = img.size
    scale = size / min(w, h)
    img = img.resize(
        (max(size, int(round(w * scale))), max(size, int(round(h * scale)))),
        Image.BILINEAR,
    )
    w, h = img.size
    x0, y0 = (w - size) // 2, (h - size) // 2
    return np.asarray(img.crop((x0, y0, x0 + size, y0 + size)), np.uint8)


_IMG_EXTS = {".jpg", ".jpeg", ".png", ".bmp", ".webp"}


def prepare_imagefolder(
    src_dir: str | Path, cache_dir: str | Path, *, size: int = 256
) -> Path:
    """Decode a class-subdirectory image tree into a memmap-able u8 cache.

    Layout in: ``src_dir/<class_name>/*.jpg`` (the torchvision ImageFolder /
    ImageNet "train" convention). Layout out: ``cache_dir/images.npy``
    (``[N, size, size, 3] u8``, written incrementally via ``open_memmap`` so
    ImageNet-scale sets never materialize in RAM), ``labels.npy``,
    ``classes.txt``. Returns ``cache_dir``.

    The fixed-size u8 cache is the TPU-era answer to the reference's
    per-worker JPEG-decode input pipelines: decode once offline, then the
    native C++ pipeline random-resized-crops straight out of the OS page
    cache at train time (SURVEY.md §7 hard-part 3).
    """
    from PIL import Image

    src_dir, cache_dir = Path(src_dir), Path(cache_dir)
    # "_"-prefixed dirs are cache/metadata (e.g. _cache_train_256), never
    # classes — including one would silently shift every label index.
    classes = sorted(
        d.name for d in src_dir.iterdir() if d.is_dir() and not d.name.startswith("_")
    )
    if not classes:
        raise FileNotFoundError(f"no class subdirectories under {src_dir}")
    files: list[tuple[Path, int]] = []
    for label, cls in enumerate(classes):
        for p in sorted((src_dir / cls).rglob("*")):
            if p.suffix.lower() in _IMG_EXTS:
                files.append((p, label))
    if not files:
        raise FileNotFoundError(f"no image files under {src_dir}")
    cache_dir.mkdir(parents=True, exist_ok=True)
    images = np.lib.format.open_memmap(
        cache_dir / "images.npy",
        mode="w+",
        dtype=np.uint8,
        shape=(len(files), size, size, 3),
    )
    labels = np.empty(len(files), np.int32)
    for i, (path, label) in enumerate(files):
        with Image.open(path) as img:
            images[i] = _decode_resize_center(img, size)
        labels[i] = label
    images.flush()
    np.save(cache_dir / "labels.npy", labels)
    (cache_dir / "classes.txt").write_text("\n".join(classes) + "\n")
    return cache_dir


def prepare_tfrecords(
    files: list[str | Path],
    cache_dir: str | Path,
    *,
    size: int = 256,
    label_offset: int = 0,
) -> Path:
    """Decode ImageNet-style TFRecords into the same u8 cache layout.

    Expects ``tf.Example`` records with ``image/encoded`` (JPEG bytes) and
    ``image/class/label``. The cache is 0-based (what the loss one-hot,
    accuracy, and imagefolder caches all use); classic ILSVRC shards store
    1-based labels (0 = background), so pass ``label_offset=1`` for those —
    stored label = raw - offset, validated non-negative. Uses tf.data purely
    as a record reader/parser (SURVEY.md §7 environment note: "tf available
    for tf.data only"); pixels land in the cache once and tf never appears
    at train time.
    """
    import io

    import tensorflow as tf
    from PIL import Image

    cache_dir = Path(cache_dir)
    cache_dir.mkdir(parents=True, exist_ok=True)
    feature_spec = {
        "image/encoded": tf.io.FixedLenFeature([], tf.string),
        "image/class/label": tf.io.FixedLenFeature([], tf.int64),
    }
    paths = [str(f) for f in files]
    # Pass 1: count records (no decode) so the memmap can be sized up front
    # and pixels stream straight to disk — ImageNet-scale sets must never
    # materialize in RAM (same contract as prepare_imagefolder).
    n = sum(1 for _ in tf.data.TFRecordDataset(paths))
    if n == 0:
        raise FileNotFoundError(f"no records in {files}")
    images = np.lib.format.open_memmap(
        cache_dir / "images.npy",
        mode="w+",
        dtype=np.uint8,
        shape=(n, size, size, 3),
    )
    labels = np.empty(n, np.int32)
    for i, raw in enumerate(tf.data.TFRecordDataset(paths)):
        ex = tf.io.parse_single_example(raw, feature_spec)
        with Image.open(io.BytesIO(ex["image/encoded"].numpy())) as img:
            images[i] = _decode_resize_center(img, size)
        label = int(ex["image/class/label"].numpy()) - label_offset
        if label < 0:
            raise ValueError(
                f"record {i}: label {label + label_offset} - offset "
                f"{label_offset} is negative; wrong label_offset?"
            )
        labels[i] = label
    images.flush()
    np.save(cache_dir / "labels.npy", labels)
    return cache_dir


def load_imagefolder(
    data_dir: str | Path, split: str = "train", *, size: int = 256
) -> SyntheticClassification:
    """ImageNet-class data: u8 cache, raw imagefolder, or TFRecords.

    Resolution order under ``data_dir`` (then ``data_dir/<split>``):

    1. A prepared cache (``images.npy`` + ``labels.npy``) — memory-mapped,
       so ImageNet-scale arrays cost no RAM up front.
    2. Class subdirectories of images — prepared into
       ``data_dir/_cache_<split>_<size>`` on first use, then memory-mapped.
    3. ``<split>-*.tfrecord*`` / ``<split>-*`` TFRecord shards — same.

    Images stay uint8 ``[N, size, size, 3]``; the train-time pipeline
    (native C++ or numpy fallback) does the random-resized-crop to the model
    geometry and the 1/255 scale.
    """
    data_dir = Path(data_dir)
    if (data_dir / split).exists():
        split_dir = data_dir / split
    elif split == "train":
        # Bare layout: class dirs / shards directly under data_dir.
        split_dir = data_dir
    else:
        # Never silently serve train images as a val split.
        raise FileNotFoundError(f"no {split!r} split under {data_dir}")

    def _from_cache(cache: Path) -> SyntheticClassification:
        return SyntheticClassification(
            images=np.load(cache / "images.npy", mmap_mode="r"),
            labels=np.load(cache / "labels.npy"),
        )

    for cand in (split_dir, data_dir / f"_cache_{split}_{size}"):
        if (cand / "images.npy").exists() and (cand / "labels.npy").exists():
            return _from_cache(cand)
    cache = data_dir / f"_cache_{split}_{size}"
    if any(d.is_dir() and not d.name.startswith("_") for d in split_dir.iterdir()):
        return _from_cache(prepare_imagefolder(split_dir, cache, size=size))
    # Only genuine record shards: "*.tfrecord*" or the classic
    # "<split>-00000-of-01024" naming. Never directories or stray metadata
    # files (train_stats.json would crash the record parser mid-prepare).
    shard_re = re.compile(rf"(tfrecord|^{re.escape(split)}-\d+-of-\d+$)")
    tfrecords = sorted(
        p for p in split_dir.iterdir() if p.is_file() and shard_re.search(p.name)
    )
    if tfrecords:
        return _from_cache(prepare_tfrecords(tfrecords, cache, size=size))
    raise FileNotFoundError(
        f"no prepared cache, class subdirectories, or TFRecords under {split_dir}"
    )


_LOADERS = {"mnist": load_mnist, "cifar10": load_cifar10, "imagenet": load_imagefolder}


def load_dataset(
    name: str,
    data_dir: str | Path | None,
    *,
    split: str = "train",
    fallback_examples: int = 4096,
    image_shape: tuple[int, int, int] | None = None,
    num_classes: int = 10,
    seed: int = 0,
) -> SyntheticClassification:
    """Real data if present under ``data_dir``, else seeded synthetic.

    The synthetic fallback mirrors the requested geometry so shapes (and
    therefore compiled programs) are identical either way.
    """
    defaults = {"mnist": (28, 28, 1), "cifar10": (32, 32, 3)}
    if name in _LOADERS and data_dir is not None:
        try:
            return _LOADERS[name](data_dir, split)
        except FileNotFoundError as e:
            # The user pointed at real data and didn't get it — training on
            # synthetic noise must never look like a successful real run.
            logger.warning(
                "%s not found under %s (%s); FALLING BACK TO SYNTHETIC DATA",
                name,
                data_dir,
                e,
            )
    shape = image_shape or defaults.get(name)
    if shape is None:
        raise ValueError(f"unknown dataset {name!r} and no image_shape given")
    return synthetic_image_classification(
        fallback_examples, shape, num_classes, seed=seed
    )
