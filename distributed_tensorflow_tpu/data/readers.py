"""Real-dataset readers, gated on local file presence (zero-egress env).

Parity with the reference's per-workload input pipelines (SURVEY.md §2
"Input pipelines" row): MNIST idx files and CIFAR-10 python-pickle batches
load into the same in-memory :class:`SyntheticClassification` container the
synthetic generators produce, so every downstream component (loader,
train step, CLI) is agnostic to where the pixels came from.

``load_dataset`` is the single entry: real data when the files exist under
``data_dir``, seeded synthetic otherwise — the run never fails for lack of
a download.
"""

from __future__ import annotations

import gzip
import logging
import pickle
import struct
from pathlib import Path

import numpy as np

logger = logging.getLogger(__name__)

from distributed_tensorflow_tpu.data.synthetic import (
    SyntheticClassification,
    synthetic_image_classification,
)

_MNIST_IMAGE_MAGIC = 2051
_MNIST_LABEL_MAGIC = 2049


def _open_maybe_gz(path: Path):
    if path.suffix == ".gz":
        return gzip.open(path, "rb")
    return path.open("rb")


def _read_idx_images(path: Path) -> np.ndarray:
    with _open_maybe_gz(path) as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != _MNIST_IMAGE_MAGIC:
            raise ValueError(f"{path}: bad idx image magic {magic}")
        data = np.frombuffer(f.read(n * rows * cols), np.uint8)
    return data.reshape(n, rows, cols, 1)


def _read_idx_labels(path: Path) -> np.ndarray:
    with _open_maybe_gz(path) as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != _MNIST_LABEL_MAGIC:
            raise ValueError(f"{path}: bad idx label magic {magic}")
        return np.frombuffer(f.read(n), np.uint8).astype(np.int32)


def _find(data_dir: Path, names: list[str]) -> Path | None:
    for name in names:
        for cand in (data_dir / name, data_dir / (name + ".gz")):
            if cand.exists():
                return cand
    return None


def load_mnist(data_dir: str | Path, split: str = "train") -> SyntheticClassification:
    """MNIST from idx files (optionally .gz). Pixels scaled to [0, 1]."""
    data_dir = Path(data_dir)
    prefix = "train" if split == "train" else "t10k"
    img = _find(data_dir, [f"{prefix}-images-idx3-ubyte", f"{prefix}-images.idx3-ubyte"])
    lab = _find(data_dir, [f"{prefix}-labels-idx1-ubyte", f"{prefix}-labels.idx1-ubyte"])
    if img is None or lab is None:
        raise FileNotFoundError(f"no MNIST {split} idx files under {data_dir}")
    images = _read_idx_images(img).astype(np.float32) / 255.0
    labels = _read_idx_labels(lab)
    if len(images) != len(labels):
        raise ValueError(f"{len(images)} images vs {len(labels)} labels")
    return SyntheticClassification(images=images, labels=labels)


def load_cifar10(
    data_dir: str | Path, split: str = "train"
) -> SyntheticClassification:
    """CIFAR-10 from the python-version pickle batches. NHWC [0, 1] float."""
    data_dir = Path(data_dir)
    base = data_dir / "cifar-10-batches-py"
    if not base.exists():
        base = data_dir
    names = (
        [f"data_batch_{i}" for i in range(1, 6)] if split == "train" else ["test_batch"]
    )
    images, labels = [], []
    for name in names:
        path = base / name
        if not path.exists():
            raise FileNotFoundError(f"missing CIFAR-10 batch {path}")
        with path.open("rb") as f:
            d = pickle.load(f, encoding="bytes")
        raw = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        images.append(raw.astype(np.float32) / 255.0)
        labels.append(np.asarray(d[b"labels"], np.int32))
    return SyntheticClassification(
        images=np.concatenate(images), labels=np.concatenate(labels)
    )


_LOADERS = {"mnist": load_mnist, "cifar10": load_cifar10}


def load_dataset(
    name: str,
    data_dir: str | Path | None,
    *,
    split: str = "train",
    fallback_examples: int = 4096,
    image_shape: tuple[int, int, int] | None = None,
    num_classes: int = 10,
    seed: int = 0,
) -> SyntheticClassification:
    """Real data if present under ``data_dir``, else seeded synthetic.

    The synthetic fallback mirrors the requested geometry so shapes (and
    therefore compiled programs) are identical either way.
    """
    defaults = {"mnist": (28, 28, 1), "cifar10": (32, 32, 3)}
    if name in _LOADERS and data_dir is not None:
        try:
            return _LOADERS[name](data_dir, split)
        except FileNotFoundError as e:
            # The user pointed at real data and didn't get it — training on
            # synthetic noise must never look like a successful real run.
            logger.warning(
                "%s not found under %s (%s); FALLING BACK TO SYNTHETIC DATA",
                name,
                data_dir,
                e,
            )
    shape = image_shape or defaults.get(name)
    if shape is None:
        raise ValueError(f"unknown dataset {name!r} and no image_shape given")
    return synthetic_image_classification(
        fallback_examples, shape, num_classes, seed=seed
    )
