"""Host→mesh batch assembly: the SPMD input path.

The reference's workers each feed their local ``sess.run`` from a per-worker
reader (SURVEY.md §3b); sharding is implicit in "each worker reads different
files". Here sharding is explicit: each host builds its process-local slice
of the global batch and the loader assembles one global ``jax.Array`` per
leaf with the batch sharded over the DP mesh axes.
"""

from __future__ import annotations

from collections.abc import Iterator

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.data.synthetic import SyntheticClassification
from distributed_tensorflow_tpu.parallel.mesh import batch_pspec, data_axes


def _global_batch_layout(mesh, global_batch: int):
    """Shared validation + sharding for global-batch producers.

    Returns ``(sharding, process_index, local_batch)`` after checking the
    global batch divides both the DP world size and the host count.
    """
    n_dp = int(np.prod([mesh.shape[a] for a in data_axes(mesh)], initial=1))
    if global_batch % n_dp:
        raise ValueError(
            f"global batch {global_batch} not divisible by DP world size {n_dp}"
        )
    n_proc = jax.process_count()
    if global_batch % n_proc:
        raise ValueError(f"global batch {global_batch} not divisible by {n_proc} hosts")
    sharding = NamedSharding(mesh, batch_pspec(mesh))
    return sharding, jax.process_index(), global_batch // n_proc


def device_batches(
    dataset: SyntheticClassification,
    mesh,
    global_batch: int,
    *,
    seed: int = 0,
) -> Iterator[dict]:
    """Infinite iterator of global batches sharded over the mesh's DP axes.

    Each epoch reshuffles with a deterministic per-epoch seed; the tail
    examples that don't fill a global batch are dropped (static shapes only —
    a partial batch would force an XLA recompile). In multi-host jobs every
    host computes the same permutation (same seed) and takes its own
    contiguous slice — the no-coordination equivalent of
    ``tf.data.Dataset.shard(num_hosts, host_id)`` (SURVEY.md §7 step 5).
    """
    n = len(dataset)
    if global_batch > n:
        raise ValueError(f"global batch {global_batch} > dataset size {n}")
    sharding, proc, local_b = _global_batch_layout(mesh, global_batch)
    epoch = 0
    while True:
        order = np.random.default_rng(seed + epoch).permutation(n)
        for start in range(0, n - global_batch + 1, global_batch):
            idx = order[start + proc * local_b : start + (proc + 1) * local_b]
            local = {
                "image": dataset.images[idx],
                "label": dataset.labels[idx],
            }
            yield {
                k: jax.make_array_from_process_local_data(sharding, v)
                for k, v in local.items()
            }
        epoch += 1


def native_device_batches(
    dataset: SyntheticClassification,
    mesh,
    global_batch: int,
    *,
    pad: int = 0,
    flip: bool = False,
    standardize: bool = False,
    seed: int = 0,
    n_threads: int = 4,
) -> Iterator[dict]:
    """Like :func:`device_batches` but fed by the native C++ pipeline.

    Augmentation (pad-crop/flip/standardize) and batch staging run in the
    C++ worker pool (data/native.py) off the Python thread, so host-side
    preprocessing overlaps the device step. Sampling is uniform with
    replacement (per-host independent streams via the seed), deterministic
    for a fixed seed regardless of thread count. Raises RuntimeError when
    the native library can't be built — callers fall back to
    :func:`device_batches`.
    """
    from distributed_tensorflow_tpu.data.native import NativePipeline

    sharding, proc, local_b = _global_batch_layout(mesh, global_batch)
    pipe = NativePipeline(
        dataset.images,
        dataset.labels,
        batch=local_b,
        pad=pad,
        flip=flip,
        standardize=standardize,
        seed=seed * 1000003 + proc,
        n_threads=n_threads,
    )
    while True:
        images, labels = pipe.next()
        yield {
            "image": jax.make_array_from_process_local_data(sharding, images),
            "label": jax.make_array_from_process_local_data(sharding, labels),
        }
