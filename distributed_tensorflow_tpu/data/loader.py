"""Host→mesh batch assembly: the SPMD input path.

The reference's workers each feed their local ``sess.run`` from a per-worker
reader (SURVEY.md §3b); sharding is implicit in "each worker reads different
files". Here sharding is explicit: each host builds its process-local slice
of the global batch and the loader assembles one global ``jax.Array`` per
leaf with the batch sharded over the DP mesh axes.

Both producers are **stream-position indexed**: batch ``k`` of a run is a
pure function of ``(seed, k)``, so a checkpoint-restored run passes
``start_step=N`` and consumes batches ``N, N+1, ...`` — never replaying
``0..N-1`` (the resume-correctness the reference's stateful queue runners
could not give).

Both run assembly + device placement inline in ``next()`` — wrap with
``data.prefetch`` to move that work onto a feeder thread and off the step
stream's critical path (the stream contract is unaffected: the wrapper
consumes in order and never skips).
"""

from __future__ import annotations

from collections.abc import Iterator

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.data.synthetic import SyntheticClassification
from distributed_tensorflow_tpu.parallel.mesh import batch_pspec, local_batch_size


def _global_batch_layout(mesh, global_batch: int):
    """Shared validation + sharding for global-batch producers.

    Returns ``(sharding, process_index, local_batch)``;
    ``local_batch_size`` does the divisibility validation (DP world size and
    host count).
    """
    local_b = local_batch_size(global_batch, mesh)
    sharding = NamedSharding(mesh, batch_pspec(mesh))
    return sharding, jax.process_index(), local_b


def _center_crop(images: np.ndarray, out_hw: tuple[int, int]) -> np.ndarray:
    h, w = images.shape[1:3]
    oh, ow = out_hw
    y0, x0 = max(0, (h - oh) // 2), max(0, (w - ow) // 2)
    return images[:, y0 : y0 + oh, x0 : x0 + ow]


def device_batches(
    dataset: SyntheticClassification,
    mesh,
    global_batch: int,
    *,
    seed: int = 0,
    start_step: int = 0,
    out_size: tuple[int, int] | None = None,
    mean: np.ndarray | None = None,
    stddev: np.ndarray | None = None,
    out_dtype: str = "float32",
) -> Iterator[dict]:
    """Infinite iterator of global batches sharded over the mesh's DP axes.

    Each epoch reshuffles with a deterministic per-epoch seed; the tail
    examples that don't fill a global batch are dropped (static shapes only —
    a partial batch would force an XLA recompile). In multi-host jobs every
    host computes the same permutation (same seed) and takes its own
    contiguous slice — the no-coordination equivalent of
    ``tf.data.Dataset.shard(num_hosts, host_id)`` (SURVEY.md §7 step 5).

    ``start_step`` starts the stream at batch N (resume). uint8 datasets are
    scaled to [0, 1] float; ``out_size`` center-crops (the numpy fallback for
    the native pipeline's crop-resize path). ``out_dtype="bfloat16"``
    narrows the assembled image batch at copy-out (augmentation math stays
    float32), halving the host→device image bytes — the numpy mirror of
    the native pipeline's ``out_dtype``.
    """
    from distributed_tensorflow_tpu.data.native import resolve_input_dtype

    np_out = resolve_input_dtype(out_dtype)
    n = len(dataset)
    if global_batch > n:
        raise ValueError(f"global batch {global_batch} > dataset size {n}")
    sharding, proc, local_b = _global_batch_layout(mesh, global_batch)
    batches_per_epoch = n // global_batch
    step = start_step
    epoch, order = -1, None
    while True:
        e, slot = divmod(step, batches_per_epoch)
        if e != epoch:
            epoch, order = e, np.random.default_rng(seed + e).permutation(n)
        lo = slot * global_batch + proc * local_b
        idx = order[lo : lo + local_b]
        images = dataset.images[idx]
        # Crop BEFORE the u8->f32 scale: per-pixel work then touches only
        # surviving pixels (224² of a 256² store is 23% less convert
        # traffic in the assembly hot path). Bit-identical output — crop
        # commutes with the elementwise ops.
        if out_size is not None and images.shape[1:3] != tuple(out_size):
            images = _center_crop(images, out_size)
        if images.dtype == np.uint8:
            images = images.astype(np.float32) / 255.0
        if mean is not None:
            images = (images - mean) / stddev
        local = {
            "image": np.ascontiguousarray(images, np.float32).astype(
                np_out, copy=False
            ),
            "label": dataset.labels[idx],
        }
        yield {
            k: jax.make_array_from_process_local_data(sharding, v)
            for k, v in local.items()
        }
        step += 1


def native_device_batches(
    dataset: SyntheticClassification,
    mesh,
    global_batch: int,
    *,
    out_size: tuple[int, int] | None = None,
    pad: int = 0,
    flip: bool = False,
    standardize: bool = False,
    rrc: bool = False,
    mean: np.ndarray | None = None,
    stddev: np.ndarray | None = None,
    seed: int = 0,
    start_step: int = 0,
    n_threads: int = 4,
    out_dtype: str = "float32",
) -> Iterator[dict]:
    """Like :func:`device_batches` but fed by the native C++ pipeline.

    Augmentation (pad-crop/flip/standardize, or random-resized-crop +
    per-channel normalization for ImageNet-style datasets) and batch staging
    run in the C++ worker pool (data/native.py) off the Python thread, so
    host-side preprocessing overlaps the device step. Sampling is per-epoch
    permutation without replacement; all hosts share the epoch permutation
    (same seed) and read disjoint strided slices. ``start_step`` resumes the
    stream at batch N. Raises RuntimeError when the native library can't be
    built — callers fall back to :func:`device_batches`.
    """
    from distributed_tensorflow_tpu.data.native import NativePipeline

    if global_batch > len(dataset):
        raise ValueError(f"global batch {global_batch} > dataset size {len(dataset)}")
    sharding, proc, local_b = _global_batch_layout(mesh, global_batch)
    pipe = NativePipeline(
        dataset.images,
        dataset.labels,
        batch=local_b,
        out_size=out_size,
        pad=pad,
        flip=flip,
        standardize=standardize,
        rrc=rrc,
        mean=mean,
        stddev=stddev,
        seed=seed,
        stream_offset=proc * local_b,
        stream_stride=global_batch,
        start_ticket=start_step,
        n_threads=n_threads,
        out_dtype=out_dtype,
    )
    try:
        while True:
            images, labels = pipe.next()
            yield {
                "image": jax.make_array_from_process_local_data(sharding, images),
                "label": jax.make_array_from_process_local_data(sharding, labels),
            }
    finally:
        pipe.close()
