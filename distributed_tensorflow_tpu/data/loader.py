"""Host→mesh batch assembly: the SPMD input path.

The reference's workers each feed their local ``sess.run`` from a per-worker
reader (SURVEY.md §3b); sharding is implicit in "each worker reads different
files". Here sharding is explicit: each host builds its process-local slice
of the global batch and the loader assembles one global ``jax.Array`` per
leaf with the batch sharded over the DP mesh axes.
"""

from __future__ import annotations

from collections.abc import Iterator

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.data.synthetic import SyntheticClassification
from distributed_tensorflow_tpu.parallel.mesh import batch_pspec, data_axes


def device_batches(
    dataset: SyntheticClassification,
    mesh,
    global_batch: int,
    *,
    seed: int = 0,
) -> Iterator[dict]:
    """Infinite iterator of global batches sharded over the mesh's DP axes.

    Each epoch reshuffles with a deterministic per-epoch seed; the tail
    examples that don't fill a global batch are dropped (static shapes only —
    a partial batch would force an XLA recompile). In multi-host jobs every
    host computes the same permutation (same seed) and takes its own
    contiguous slice — the no-coordination equivalent of
    ``tf.data.Dataset.shard(num_hosts, host_id)`` (SURVEY.md §7 step 5).
    """
    n = len(dataset)
    if global_batch > n:
        raise ValueError(f"global batch {global_batch} > dataset size {n}")
    n_dp = int(np.prod([mesh.shape[a] for a in data_axes(mesh)], initial=1))
    if global_batch % n_dp:
        raise ValueError(
            f"global batch {global_batch} not divisible by DP world size {n_dp}"
        )
    sharding = NamedSharding(mesh, batch_pspec(mesh))
    n_proc = jax.process_count()
    proc = jax.process_index()
    if global_batch % n_proc:
        raise ValueError(f"global batch {global_batch} not divisible by {n_proc} hosts")
    local_b = global_batch // n_proc
    epoch = 0
    while True:
        order = np.random.default_rng(seed + epoch).permutation(n)
        for start in range(0, n - global_batch + 1, global_batch):
            idx = order[start + proc * local_b : start + (proc + 1) * local_b]
            local = {
                "image": dataset.images[idx],
                "label": dataset.labels[idx],
            }
            yield {
                k: jax.make_array_from_process_local_data(sharding, v)
                for k, v in local.items()
            }
        epoch += 1
