"""Asynchronous feed stage: bounded background-thread prefetch.

The reference fed each worker's ``sess.run`` from queue runners — input
assembly ran on background threads and the step never waited on the host in
steady state (SURVEY.md §3b). The rebuild's explicit SPMD loaders lost that
overlap: every producer in this package does numpy assembly *and* the
host→device transfer inline in ``next()``. This module restores the overlap
as a composable stage: :func:`prefetch` wraps any batch iterator
(``device_batches``, the text/BERT producers, the native C++ pipeline
stream) with a feeder thread that runs the wrapped producer ``depth``
batches ahead, so stages (1) host assembly, (2) host→device transfer, and
(3) device compute pipeline instead of serializing — the tf.data
``prefetch(AUTOTUNE)`` discipline applied to our loaders.

Determinism contract: the wrapped producer is consumed **in order by
exactly one feeder thread**, and batches cross a FIFO queue, so batch ``k``
is still a pure function of ``(seed, k)`` — ``prefetch(it, 0)`` and
``prefetch(it, N)`` yield bit-identical streams, and checkpoint resume via
the producers' ``start_step`` composes unchanged (the wrapper never skips
or reorders). Asserted by ``tests/test_prefetch.py``.

Error handling: a feeder-thread exception is re-raised by the consumer's
very next ``__next__`` after the buffered good batches drain — the loop
fails loudly, never hangs. ``close()`` stops the thread and closes the
wrapped producer (releasing e.g. the native pipeline's C++ worker pool).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from collections.abc import Iterable, Iterator

from distributed_tensorflow_tpu.obs.metrics import FeedMetrics

logger = logging.getLogger(__name__)

_ITEM, _END, _ERROR = 0, 1, 2


class PrefetchIterator:
    """Iterator running ``source`` on a feeder thread, ``depth`` batches ahead.

    The feeder does everything the wrapped producer does inline — numpy
    assembly and ``jax`` device placement — off the consumer's critical
    path, bounded by a ``depth``-slot FIFO queue (bounded, so a stalled
    consumer exerts backpressure instead of buffering the whole epoch in
    host RAM). Feeder-side metrics (assembly time, queue depth, batches
    assembled) land in ``self.metrics``; the *consumer* owns the host-wait
    measurement (``metrics.observe_wait``), because only the consumption
    point knows how long the step stream actually stalled.

    Single-consumer: ``__next__`` may be called from one thread at a time
    (the training loop's pull-ahead structure satisfies this by
    construction).
    """

    # Watched by obs.sanitizer.sanitize_races in the prefetch soaks:
    # consumer-side flags (_done) plus the close handshake (_closed).
    _RACETRACE_ATTRS = ("_done", "_closed")

    def __init__(
        self,
        source: Iterable,
        depth: int = 2,
        *,
        metrics: FeedMetrics | None = None,
        name: str = "feed-prefetch",
        fault_injector=None,
    ):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.metrics = metrics if metrics is not None else FeedMetrics()
        self.depth = depth
        self._injector = fault_injector
        self._source = source
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._done = False
        # _close_lock makes close() idempotent under concurrent callers:
        # only the winner of the closed check runs the drain/join sequence.
        # The drain itself stays OUTSIDE the lock — holding it across
        # Thread.join would reintroduce the blocking-under-lock hazard.
        self._close_lock = threading.Lock()
        self._closed = False
        self._thread = threading.Thread(target=self._feed, name=name, daemon=True)
        self._thread.start()

    # ---- feeder side -----------------------------------------------------

    def _feed(self) -> None:
        m = self.metrics
        try:
            it = iter(self._source)
            index = 0
            while not self._stop.is_set():
                if self._injector is not None:
                    # Injected feeder fault (train/faultinject.py): raised
                    # HERE on the feeder thread so it reaches the consumer
                    # through the real _ERROR channel below.
                    self._injector.check_feeder(index)
                index += 1
                t0 = time.perf_counter()
                try:
                    item = next(it)
                except StopIteration:
                    self._enqueue((_END, None))
                    return
                m.assembly.observe(time.perf_counter() - t0)
                m.batches_assembled.inc()
                if not self._enqueue((_ITEM, item)):
                    return
        except BaseException as e:  # noqa: BLE001 — must reach the consumer
            self._enqueue((_ERROR, e))

    def _enqueue(self, msg) -> bool:
        """Bounded put that aborts (returns False) once close() is called."""
        while not self._stop.is_set():
            try:
                self._q.put(msg, timeout=0.05)
            except queue.Full:
                continue
            self.metrics.queue_depth.set(self._q.qsize())
            return True
        return False

    # ---- consumer side ---------------------------------------------------

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._closed:
            raise RuntimeError("prefetch iterator is closed")
        if self._done:
            raise StopIteration
        while True:
            try:
                tag, val = self._q.get(timeout=1.0)
                break
            except queue.Empty:
                # The feeder always enqueues _END/_ERROR before exiting; an
                # empty queue with a dead thread means it was killed hard —
                # fail loudly rather than block forever.
                if not self._thread.is_alive():
                    raise RuntimeError(
                        "prefetch feeder thread died without reporting"
                    ) from None
        self.metrics.queue_depth.set(self._q.qsize())
        if tag == _END:
            self._done = True
            raise StopIteration
        if tag == _ERROR:
            self._done = True
            raise val
        return val

    def close(self, join_timeout_s: float = 5.0) -> None:
        """Stop the feeder and close the wrapped producer (idempotent)."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        # Drain buffered batches so a feeder blocked in put() wakes promptly
        # (its 50 ms poll would also catch the stop flag) and device/host
        # buffers are released.
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(join_timeout_s)
        close = getattr(self._source, "close", None)
        if close is None:
            return
        if self._thread.is_alive():
            # Feeder wedged inside the producer: closing a generator that is
            # mid-next() raises ValueError — try anyway (non-generator
            # sources like NativePipeline unblock their own next()).
            logger.warning("prefetch feeder did not stop in %.1fs", join_timeout_s)
            try:
                close()
            except ValueError:
                pass
        else:
            close()


class _SyncFeed:
    """The prefetch-disabled path with the same observability surface.

    ``next()`` runs the producer inline — assembly time is recorded (so the
    ``batches_assembled`` counter and ``assembly`` histogram stay
    meaningful for A/B runs) but nothing is hidden: the consumer's measured
    host wait will equal the full assembly cost. ``prefetch 0`` therefore
    answers "how feed-bound is this run?" with the same metrics the async
    path reports.
    """

    def __init__(
        self,
        source: Iterable,
        *,
        metrics: FeedMetrics | None = None,
        fault_injector=None,
    ):
        self.metrics = metrics if metrics is not None else FeedMetrics()
        self.depth = 0
        self._injector = fault_injector
        self._index = 0
        self._source = source
        self._it = iter(source)

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._injector is not None:
            self._injector.check_feeder(self._index)
        self._index += 1
        t0 = time.perf_counter()
        item = next(self._it)
        self.metrics.assembly.observe(time.perf_counter() - t0)
        self.metrics.batches_assembled.inc()
        return item

    def close(self) -> None:
        close = getattr(self._source, "close", None)
        if close is not None:
            close()


def prefetch(
    source: Iterable,
    depth: int = 2,
    *,
    metrics: FeedMetrics | None = None,
    fault_injector=None,
) -> PrefetchIterator | _SyncFeed:
    """Wrap a batch producer with ``depth`` batches of background prefetch.

    ``depth >= 1`` returns a :class:`PrefetchIterator` (feeder thread +
    bounded queue); ``depth <= 0`` returns the synchronous passthrough with
    identical metrics/close surface, so call sites and A/B comparisons
    need no branching. Default depth 2: one batch in host→device flight
    while the next assembles — deeper queues only buy slack against
    assembly-time jitter, at ``depth`` batches of extra host RAM.

    ``fault_injector`` (train/faultinject.py) is consulted before each
    produced batch — scheduled ``feeder_error`` events fire inside the
    feed stage exactly where a real producer failure would, on either
    path. Event indices count batches produced by THIS wrapper instance
    (a resumed run's new wrapper counts from 0 again).
    """
    if depth <= 0:
        return _SyncFeed(source, metrics=metrics, fault_injector=fault_injector)
    return PrefetchIterator(
        source, depth, metrics=metrics, fault_injector=fault_injector
    )
