"""Input pipelines: per-host sharded data feeding the SPMD step.

Replaces the reference's per-workload input pipelines (SURVEY.md §2 "Input
pipelines" row). Where each reference worker read its own shard and fed its
own ``sess.run``, here each host materializes its slice of the global batch
and assembles a global ``jax.Array`` over the mesh
(``jax.make_array_from_process_local_data``) — same sharding idea, no
per-role code.

Real-dataset readers are gated on local file presence (this environment has
zero egress); the synthetic generators produce seeded, learnably-structured
data so convergence tests are meaningful without downloads.

Every producer composes with :func:`prefetch` (data/prefetch.py): a bounded
feeder thread runs assembly + host→device transfer ahead of the step
stream — the queue-runner overlap the reference had, without its
nondeterminism (batch ``k`` stays a pure function of ``(seed, k)``).
"""

from distributed_tensorflow_tpu.data.synthetic import (  # noqa: F401
    SyntheticClassification,
    synthetic_image_classification,
)
from distributed_tensorflow_tpu.data.loader import (  # noqa: F401
    device_batches,
    native_device_batches,
)
from distributed_tensorflow_tpu.data.prefetch import (  # noqa: F401
    PrefetchIterator,
    prefetch,
)
from distributed_tensorflow_tpu.data.text import (  # noqa: F401
    SyntheticLM,
    SyntheticMLM,
    SyntheticMLMConfig,
    bert_batch_specs,
    lm_batch_specs,
    mlm_device_batches,
)
