"""Synthetic masked-LM pretraining data (zero-egress stand-in for BERT corpora).

Token streams follow a fixed random Markov chain (token_{t+1} =
perm[token_t] with occasional uniform noise), so MLM is genuinely learnable
from bidirectional context; sentence pairs either continue the chain
(NSP label 0, "is next") or jump to an unrelated chain (label 1). BERT-style
masking: 15% of positions — 80% → [MASK], 10% → random, 10% kept.

Vocab layout: 0=[PAD] 1=[CLS] 2=[SEP] 3=[MASK], content tokens 4..vocab-1.
"""

from __future__ import annotations

import dataclasses

import numpy as np

PAD, CLS, SEP, MASK = 0, 1, 2, 3
NUM_SPECIAL = 4


@dataclasses.dataclass
class SyntheticMLMConfig:
    vocab_size: int = 1000
    seq_len: int = 128
    mask_prob: float = 0.15
    noise: float = 0.05  # chance a chain step jumps uniformly
    seed: int = 0


class SyntheticMLM:
    """Generates BERT pretraining batches: ids/mask/types/mlm targets/nsp."""

    def __init__(self, cfg: SyntheticMLMConfig):
        assert cfg.vocab_size > NUM_SPECIAL + 1
        self.cfg = cfg
        rng = np.random.default_rng(cfg.seed)
        n_content = cfg.vocab_size - NUM_SPECIAL
        self._perm = rng.permutation(n_content)

    def _chains(self, rng, nrows: int, length: int) -> np.ndarray:
        """Vectorized Markov chains: [nrows, length] content tokens."""
        n = self.cfg.vocab_size - NUM_SPECIAL
        out = np.empty((nrows, length), np.int64)
        tok = rng.integers(0, n, nrows)
        for i in range(length):
            out[:, i] = tok
            jump = rng.random(nrows) < self.cfg.noise
            tok = np.where(jump, rng.integers(0, n, nrows), self._perm[tok])
        return out + NUM_SPECIAL

    def batch(
        self, batch_size: int, *, seed: int | tuple[int, ...]
    ) -> dict[str, np.ndarray]:
        """One batch, fully vectorized (the step-loop hot path on host)."""
        cfg = self.cfg
        key = (seed,) if isinstance(seed, int) else tuple(seed)
        rng = np.random.default_rng((cfg.seed, *key))
        L = cfg.seq_len
        # [CLS] a... [SEP] b... [SEP] — split content evenly.
        n_a = (L - 3) // 2
        n_b = L - 3 - n_a
        a = self._chains(rng, batch_size, n_a + n_b)
        b_new = self._chains(rng, batch_size, n_b)
        nsp = (rng.random(batch_size) < 0.5).astype(np.int32)  # 1 = random b
        b = np.where(nsp[:, None] == 1, b_new, a[:, n_a:])
        ids = np.empty((batch_size, L), np.int32)
        ids[:, 0] = CLS
        ids[:, 1 : n_a + 1] = a[:, :n_a]
        ids[:, n_a + 1] = SEP
        ids[:, n_a + 2 : n_a + 2 + n_b] = b
        ids[:, -1] = SEP
        types = np.zeros((batch_size, L), np.int32)
        types[:, n_a + 2 :] = 1
        attention_mask = np.ones((batch_size, L), bool)

        # BERT masking on content positions only.
        content = ids >= NUM_SPECIAL
        r = rng.random(ids.shape)
        selected = content & (r < cfg.mask_prob)
        targets = np.where(selected, ids, -1).astype(np.int32)
        action = rng.random(ids.shape)
        masked_ids = ids.copy()
        masked_ids[selected & (action < 0.8)] = MASK
        rand_sites = selected & (action >= 0.8) & (action < 0.9)
        masked_ids[rand_sites] = rng.integers(
            NUM_SPECIAL, cfg.vocab_size, size=int(rand_sites.sum())
        )
        return {
            "input_ids": masked_ids,
            "attention_mask": attention_mask,
            "token_type_ids": types,
            "mlm_targets": targets,
            "nsp_label": nsp,
        }


def bert_batch_specs(mesh, *, seq_sharded: bool = False) -> dict:
    """Per-leaf PartitionSpecs for a BERT batch (pass as train-step batch_spec).

    [B, L] leaves shard batch over the DP axes and (optionally) sequence over
    ``"seq"``; the [B] nsp label only shards the batch dim.
    """
    from jax.sharding import PartitionSpec as P

    from distributed_tensorflow_tpu.parallel.mesh import data_axes

    dp = data_axes(mesh)
    dp_spec = dp if dp else None
    seq = "seq" if (seq_sharded and "seq" in mesh.axis_names) else None
    spec_2d = P(dp_spec, seq)
    spec_1d = P(dp_spec)
    return {
        "input_ids": spec_2d,
        "attention_mask": spec_2d,
        "token_type_ids": spec_2d,
        "mlm_targets": spec_2d,
        "nsp_label": spec_1d,
    }


def mlm_device_batches(
    dataset: SyntheticMLM,
    mesh,
    global_batch: int,
    *,
    seq_sharded: bool = False,
    seed: int = 0,
    start_step: int = 0,
):
    """Infinite iterator of placed BERT batches.

    ``seq_sharded=True`` additionally shards the [B, L] leaves' second dim
    over the mesh's ``"seq"`` axis (for ring-attention runs). Each host
    generates ONLY its local slice (per-host generator streams seeded by
    ``(step, process_index)``) — no redundant global-batch work in the hot
    loop.
    """
    import numpy as np
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu.parallel.mesh import data_axes

    dp = data_axes(mesh)
    dp_spec = dp if dp else None
    n_dp = int(np.prod([mesh.shape[a] for a in dp], initial=1))
    if global_batch % n_dp:
        raise ValueError(
            f"global batch {global_batch} not divisible by DP world size {n_dp}"
        )
    seq = "seq" if (seq_sharded and "seq" in mesh.axis_names) else None
    spec_2d = NamedSharding(mesh, P(dp_spec, seq))
    spec_1d = NamedSharding(mesh, P(dp_spec))
    n_proc = jax.process_count()
    proc = jax.process_index()
    if global_batch % n_proc:
        raise ValueError(f"global batch {global_batch} not divisible by {n_proc} hosts")
    local_b = global_batch // n_proc
    # Stream-position indexed: batch k is a pure function of (seed, k), so a
    # restored run resumes at batch N instead of replaying 0..N-1.
    step = start_step
    while True:
        local = dataset.batch(local_b, seed=(seed, step, proc))
        yield {
            k: jax.make_array_from_process_local_data(
                spec_1d if v.ndim == 1 else spec_2d, v
            )
            for k, v in local.items()
        }
        step += 1
