"""Synthetic masked-LM pretraining data (zero-egress stand-in for BERT corpora).

Token streams follow a fixed random Markov chain (token_{t+1} =
perm[token_t] with occasional uniform noise), so MLM is genuinely learnable
from bidirectional context; sentence pairs either continue the chain
(NSP label 0, "is next") or jump to an unrelated chain (label 1). BERT-style
masking: 15% of positions — 80% → [MASK], 10% → random, 10% kept.

Vocab layout: 0=[PAD] 1=[CLS] 2=[SEP] 3=[MASK], content tokens 4..vocab-1.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

PAD, CLS, SEP, MASK = 0, 1, 2, 3
NUM_SPECIAL = 4


def _apply_bert_masking(rng, ids, mask_prob, rand_lo, rand_hi):
    """The BERT masking recipe, shared by every MLM dataset: select
    ``mask_prob`` of content positions (``ids >= NUM_SPECIAL``), then
    80% → [MASK], 10% → random token from ``[rand_lo, rand_hi)``, 10% kept.
    Returns ``(masked_ids, targets)`` with ``targets = -1`` off-selection.

    Draw order (selection r, action, random replacements) is part of the
    determinism contract — changing it changes every seeded batch.
    """
    content = ids >= NUM_SPECIAL
    r = rng.random(ids.shape)
    selected = content & (r < mask_prob)
    targets = np.where(selected, ids, -1).astype(np.int32)
    action = rng.random(ids.shape)
    masked_ids = ids.copy()
    masked_ids[selected & (action < 0.8)] = MASK
    rand_sites = selected & (action >= 0.8) & (action < 0.9)
    masked_ids[rand_sites] = rng.integers(
        rand_lo, rand_hi, size=int(rand_sites.sum())
    )
    return masked_ids, targets


@dataclasses.dataclass
class SyntheticMLMConfig:
    vocab_size: int = 1000
    seq_len: int = 128
    mask_prob: float = 0.15
    noise: float = 0.05  # chance a chain step jumps uniformly
    seed: int = 0


class SyntheticMLM:
    """Generates BERT pretraining batches: ids/mask/types/mlm targets/nsp."""

    def __init__(self, cfg: SyntheticMLMConfig):
        assert cfg.vocab_size > NUM_SPECIAL + 1
        self.cfg = cfg
        rng = np.random.default_rng(cfg.seed)
        n_content = cfg.vocab_size - NUM_SPECIAL
        self._perm = rng.permutation(n_content)

    def _chains(self, rng, nrows: int, length: int) -> np.ndarray:
        """Vectorized Markov chains: [nrows, length] content tokens."""
        n = self.cfg.vocab_size - NUM_SPECIAL
        out = np.empty((nrows, length), np.int64)
        tok = rng.integers(0, n, nrows)
        for i in range(length):
            out[:, i] = tok
            jump = rng.random(nrows) < self.cfg.noise
            tok = np.where(jump, rng.integers(0, n, nrows), self._perm[tok])
        return out + NUM_SPECIAL

    def batch(
        self, batch_size: int, *, seed: int | tuple[int, ...]
    ) -> dict[str, np.ndarray]:
        """One batch, fully vectorized (the step-loop hot path on host)."""
        cfg = self.cfg
        key = (seed,) if isinstance(seed, int) else tuple(seed)
        rng = np.random.default_rng((cfg.seed, *key))
        L = cfg.seq_len
        # [CLS] a... [SEP] b... [SEP] — split content evenly.
        n_a = (L - 3) // 2
        n_b = L - 3 - n_a
        a = self._chains(rng, batch_size, n_a + n_b)
        b_new = self._chains(rng, batch_size, n_b)
        nsp = (rng.random(batch_size) < 0.5).astype(np.int32)  # 1 = random b
        b = np.where(nsp[:, None] == 1, b_new, a[:, n_a:])
        ids = np.empty((batch_size, L), np.int32)
        ids[:, 0] = CLS
        ids[:, 1 : n_a + 1] = a[:, :n_a]
        ids[:, n_a + 1] = SEP
        ids[:, n_a + 2 : n_a + 2 + n_b] = b
        ids[:, -1] = SEP
        types = np.zeros((batch_size, L), np.int32)
        types[:, n_a + 2 :] = 1
        attention_mask = np.ones((batch_size, L), bool)

        masked_ids, targets = _apply_bert_masking(
            rng, ids, cfg.mask_prob, NUM_SPECIAL, cfg.vocab_size
        )
        return {
            "input_ids": masked_ids,
            "attention_mask": attention_mask,
            "token_type_ids": types,
            "mlm_targets": targets,
            "nsp_label": nsp,
        }


class SyntheticLM(SyntheticMLM):
    """Left-to-right causal-LM batches over the same Markov chains:
    ``input_ids`` / ``attention_mask`` only (next-token prediction needs no
    masking pass, so ``cfg.mask_prob`` is unused). Row lengths vary over
    ``[L/2, L]`` with PAD tails so the shift-by-one loss weighting is
    actually exercised, not a constant."""

    def batch(
        self, batch_size: int, *, seed: int | tuple[int, ...]
    ) -> dict[str, np.ndarray]:
        cfg = self.cfg
        key = (seed,) if isinstance(seed, int) else tuple(seed)
        rng = np.random.default_rng((cfg.seed, *key))
        L = cfg.seq_len
        ids = np.empty((batch_size, L), np.int32)
        ids[:, 0] = CLS
        ids[:, 1:] = self._chains(rng, batch_size, L - 1)
        lengths = rng.integers(max(2, L // 2), L + 1, batch_size)
        attention_mask = np.arange(L)[None, :] < lengths[:, None]
        ids[~attention_mask] = PAD
        return {"input_ids": ids, "attention_mask": attention_mask}


UNK = 4
NUM_SPECIAL_TEXT = 5  # PAD CLS SEP MASK UNK

_WORD_RE = None  # compiled lazily


def _words(line: str, lowercase: bool) -> list[str]:
    global _WORD_RE
    if _WORD_RE is None:
        import re

        _WORD_RE = re.compile(r"[a-zA-Z0-9']+|[^\sa-zA-Z0-9]")
    if lowercase:
        line = line.lower()
    return _WORD_RE.findall(line)


@dataclasses.dataclass
class TextCorpusConfig:
    """Real-text BERT pretraining corpus (SURVEY.md §2 BERT workload row —
    the reference pretrained on real corpora; this is the real-data path the
    synthetic Markov stand-in gates to)."""

    seq_len: int = 128
    vocab_size: int = 30522  # cap; actual vocab may be smaller
    mask_prob: float = 0.15
    lowercase: bool = True
    seed: int = 0


class TextCorpusMLM:
    """BERT pretraining batches from plain-text files.

    Format: one sentence per line; blank lines separate documents (the
    classic BERT pretraining input convention). Tokenization is word-level
    with an [UNK] bucket (vocab = most-frequent words up to
    ``vocab_size``); masking/NSP semantics are identical to
    :class:`SyntheticMLM` (15% masked: 80/10/10; 50% random next-sentence),
    and the batch dict is interchangeable — ``mlm_device_batches`` and the
    train step don't know which one they're fed.

    Vocab layout: 0=[PAD] 1=[CLS] 2=[SEP] 3=[MASK] 4=[UNK], words 5..V-1.

    ``vocab_from``: reuse another corpus's vocabulary instead of building
    one from these files — a held-out val split must tokenize with the
    TRAIN vocab (unseen words become [UNK]) or its ids would be meaningless
    to the model.
    """

    def __init__(
        self,
        paths,
        cfg: TextCorpusConfig,
        *,
        vocab_from: "TextCorpusMLM | None" = None,
    ):
        from collections import Counter
        from pathlib import Path

        self.cfg = cfg
        sents: list[list[str]] = []
        doc_last: list[bool] = []  # True if sentence ends its document
        for path in paths:
            doc_open = False
            for line in Path(path).read_text().splitlines():
                ws = _words(line, cfg.lowercase)
                if not ws:
                    if doc_open and doc_last:
                        doc_last[-1] = True
                    doc_open = False
                    continue
                sents.append(ws)
                doc_last.append(False)
                doc_open = True
            if doc_last:
                doc_last[-1] = True
        if not sents:
            raise ValueError(f"no sentences found in {list(paths)}")
        if vocab_from is not None:
            self.vocab = vocab_from.vocab
            self._ids = vocab_from._ids
            self.vocab_size = vocab_from.vocab_size
        else:
            freq = Counter(w for s in sents for w in s)
            n_words = min(len(freq), cfg.vocab_size - NUM_SPECIAL_TEXT)
            self.vocab = [w for w, _ in freq.most_common(n_words)]
            self._ids = {w: NUM_SPECIAL_TEXT + i for i, w in enumerate(self.vocab)}
            self.vocab_size = NUM_SPECIAL_TEXT + n_words
        self._sents = [
            np.asarray([self._ids.get(w, UNK) for w in s], np.int32) for s in sents
        ]
        self._doc_last = np.asarray(doc_last)

    def _segment(self, start: int, budget: int) -> tuple[np.ndarray, int, bool]:
        """Pack consecutive sentences from ``start`` into <= budget tokens.

        Returns ``(tokens, next_idx, doc_ended)``: ``next_idx`` is the first
        sentence AFTER the ones consumed (where a true next-sentence
        continuation must start) and ``doc_ended`` whether the segment's
        document (or the corpus) ends at its last sentence — in which case
        no continuation exists.
        """
        out: list[np.ndarray] = []
        n, i = 0, start
        while True:
            s = self._sents[i]
            out.append(s[: budget - n])
            n += len(out[-1])
            at_end = bool(self._doc_last[i]) or i + 1 >= len(self._sents)
            if n >= budget or at_end:
                return np.concatenate(out), i + 1, at_end
            i += 1

    def batch(
        self, batch_size: int, *, seed: int | tuple[int, ...]
    ) -> dict[str, np.ndarray]:
        cfg = self.cfg
        key = (seed,) if isinstance(seed, int) else tuple(seed)
        rng = np.random.default_rng((cfg.seed, 1, *key))
        L = cfg.seq_len
        n_a = (L - 3) // 2
        n_b = L - 3 - n_a
        ids = np.full((batch_size, L), PAD, np.int32)
        types = np.zeros((batch_size, L), np.int32)
        nsp = (rng.random(batch_size) < 0.5).astype(np.int32)  # 1 = random b
        n_sents = len(self._sents)
        for r in range(batch_size):
            start = int(rng.integers(0, n_sents))
            a, nxt, doc_ended = self._segment(start, n_a)
            # Continuation = the sentence right after the ones A consumed,
            # same document; random = an unrelated position (NSP label 1).
            # If A ran to its document's end, no continuation exists — fall
            # back to a random segment and relabel the pair as random.
            if nsp[r] or doc_ended:
                nsp[r] = 1
                b, _, _ = self._segment(int(rng.integers(0, n_sents)), n_b)
            else:
                b, _, _ = self._segment(nxt, n_b)
            row_len = 1 + len(a) + 1 + len(b) + 1
            ids[r, 0] = CLS
            ids[r, 1 : 1 + len(a)] = a
            ids[r, 1 + len(a)] = SEP
            ids[r, 2 + len(a) : 2 + len(a) + len(b)] = b
            ids[r, row_len - 1] = SEP
            types[r, 2 + len(a) : row_len] = 1
        attention_mask = ids != PAD

        # Identical masking recipe to SyntheticMLM (content = non-special,
        # which here includes [UNK]); random replacements draw real words.
        masked_ids, targets = _apply_bert_masking(
            rng, ids, cfg.mask_prob, NUM_SPECIAL_TEXT, self.vocab_size
        )
        return {
            "input_ids": masked_ids,
            "attention_mask": attention_mask,
            "token_type_ids": types,
            "mlm_targets": targets,
            "nsp_label": nsp,
        }


def bert_batch_specs(
    mesh, *, seq_sharded: bool = False, expert_sharded: bool = False
) -> dict:
    """Per-leaf PartitionSpecs for a BERT batch (pass as train-step batch_spec).

    [B, L] leaves shard batch over the DP axes and (optionally) sequence over
    ``"seq"``; the [B] nsp label only shards the batch dim.
    ``expert_sharded=True`` additionally splits the batch dim over the
    ``"expert"`` axis — the GShard token-sharded MoE layout
    (``moe_dispatch="sharded"``), where the expert axis carries data like a
    DP axis and NOTHING in the model is redundantly replicated across it.
    """
    from distributed_tensorflow_tpu.parallel.mesh import data_axes

    dp = data_axes(mesh)
    if expert_sharded and "expert" in mesh.axis_names:
        dp = dp + ("expert",)
    dp_spec = dp if dp else None
    seq = "seq" if (seq_sharded and "seq" in mesh.axis_names) else None
    spec_2d = P(dp_spec, seq)
    spec_1d = P(dp_spec)
    return {
        "input_ids": spec_2d,
        "attention_mask": spec_2d,
        "token_type_ids": spec_2d,
        "mlm_targets": spec_2d,
        "nsp_label": spec_1d,
    }


def lm_batch_specs(mesh) -> dict:
    """Per-leaf PartitionSpecs for a causal-LM batch (ids + mask only):
    batch dim over the DP axes, sequence replicated."""
    from distributed_tensorflow_tpu.parallel.mesh import data_axes

    dp = data_axes(mesh)
    dp_spec = dp if dp else None
    return {
        "input_ids": P(dp_spec, None),
        "attention_mask": P(dp_spec, None),
    }


# Fixed generation granularity for mlm_device_batches: global row r of batch
# k always comes from chunk r // _ROW_CHUNK, whatever the host count. Every
# per-host slice must align to it (batch sizes are powers of two >= 8
# throughout).
_ROW_CHUNK = 8


def mlm_device_batches(
    dataset: SyntheticMLM,
    mesh,
    global_batch: int,
    *,
    seq_sharded: bool = False,
    expert_sharded: bool = False,
    seed: int = 0,
    start_step: int = 0,
):
    """Infinite iterator of placed BERT batches.

    ``seq_sharded=True`` additionally shards the [B, L] leaves' second dim
    over the mesh's ``"seq"`` axis (for ring-attention runs);
    ``expert_sharded=True`` splits the batch dim over ``"expert"`` too (the
    GShard token-sharded MoE layout — see :func:`bert_batch_specs`). Each
    host generates ONLY its local slice (per-host generator streams seeded
    by ``(step, process_index)``) — no redundant global-batch work in the
    hot loop.

    Chain-sampling, masking, and placement all run inline in ``next()`` —
    the generator is single-consumer by construction, so wrapping it in
    ``data.prefetch`` moves the whole per-batch cost onto the feeder
    thread without touching the ``(seed, k)`` stream contract.
    """
    from distributed_tensorflow_tpu.parallel.mesh import data_axes, local_batch_size

    dp = data_axes(mesh)
    if expert_sharded and "expert" in mesh.axis_names:
        dp = dp + ("expert",)
    dp_spec = dp if dp else None
    # With NO row-sharding axes the batch is replicated: every process must
    # materialize the FULL global batch (the equal-slice-per-host rule of
    # local_batch_size applies only when the row dim actually shards across
    # hosts — r5 cross-process pipeline rehearsal fix).
    local_b = (
        local_batch_size(
            global_batch, mesh, extra_axes=("expert",) if expert_sharded else ()
        )
        if dp
        else global_batch
    )
    seq = "seq" if (seq_sharded and "seq" in mesh.axis_names) else None
    spec_2d = NamedSharding(mesh, P(dp_spec, seq))
    spec_1d = NamedSharding(mesh, P(dp_spec))
    # HOST-COUNT-INVARIANT stream (r5): global batch k, row r is a pure
    # function of (seed, k, r // _ROW_CHUNK) — each host generates exactly
    # the fixed-size row chunks covering ITS contiguous slice, so one
    # process on a virtual mesh and N processes on a pod see the SAME
    # global data (the contract the native C++ pipeline already meets via
    # its shared epoch permutation, and what the cross-process pp/ep
    # rehearsals assert). The earlier per-process seeding made the stream
    # depend on topology — and handed different "replicated" batches to
    # different hosts in the no-data-axis case.
    start_row = jax.process_index() * local_b if dp else 0
    stop_row = start_row + local_b
    if start_row % _ROW_CHUNK or (
        local_b % _ROW_CHUNK and stop_row != global_batch
    ):
        raise ValueError(
            f"per-host batch {local_b} (offset {start_row}) must align to "
            f"the {_ROW_CHUNK}-row generation chunk"
        )
    # Chunk c's size is fixed by the GLOBAL batch (the final chunk may be
    # partial) — generation stays topology-invariant because every host
    # sizes chunk c identically.
    chunk_sizes = [
        (c, min(_ROW_CHUNK, global_batch - c * _ROW_CHUNK))
        for c in range(start_row // _ROW_CHUNK, -(-stop_row // _ROW_CHUNK))
    ]
    def place(v):
        # make_array_from_callback, not make_array_from_process_local_data:
        # the local-data API infers the global shape from the local slab, so
        # a SEQ-sharded dim spanning processes (each host holding the full L
        # while "seq" shards it) is misread as a bigger global L — position
        # ids run off the embedding table and the run NaNs (caught by the r5
        # cross-process sp rehearsal). The callback form receives each
        # addressable device's true GLOBAL index and slices both the row
        # range (shifted by this host's start_row) and the L range from the
        # locally generated rows — correct for dp, ep, seq, and any
        # composition.
        spec = spec_1d if v.ndim == 1 else spec_2d
        gshape = (global_batch,) + v.shape[1:]

        def cb(index, v=v):
            rows = index[0]
            r0 = (rows.start or 0) - start_row
            r1 = (global_batch if rows.stop is None else rows.stop) - start_row
            # Loud guard: a device whose rows fall outside this host's slab
            # (a mesh whose dp axis is not process-contiguous in device
            # order) must not wrap around via negative indexing and train
            # on silently duplicated rows.
            if r0 < 0 or r1 > len(v):
                raise ValueError(
                    f"device row range [{rows.start}, {rows.stop}) is outside "
                    f"this host's generated slab [{start_row}, {stop_row}) — "
                    "the mesh's data axis is not process-contiguous"
                )
            return v[(slice(r0, r1),) + tuple(index[1:])]

        return jax.make_array_from_callback(gshape, spec, cb)

    # Stream-position indexed: batch k is a pure function of (seed, k), so a
    # restored run resumes at batch N instead of replaying 0..N-1.
    step = start_step
    while True:
        chunks = [
            dataset.batch(size, seed=(seed, step, c))
            for c, size in chunk_sizes
        ]
        local = {
            k: np.concatenate([c[k] for c in chunks], axis=0)
            for k in chunks[0]
        }
        yield {k: place(v) for k, v in local.items()}
        step += 1
