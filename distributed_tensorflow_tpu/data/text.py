"""Synthetic masked-LM pretraining data (zero-egress stand-in for BERT corpora).

Token streams follow a fixed random Markov chain (token_{t+1} =
perm[token_t] with occasional uniform noise), so MLM is genuinely learnable
from bidirectional context; sentence pairs either continue the chain
(NSP label 0, "is next") or jump to an unrelated chain (label 1). BERT-style
masking: 15% of positions — 80% → [MASK], 10% → random, 10% kept.

Vocab layout: 0=[PAD] 1=[CLS] 2=[SEP] 3=[MASK], content tokens 4..vocab-1.
"""

from __future__ import annotations

import dataclasses

import numpy as np

PAD, CLS, SEP, MASK = 0, 1, 2, 3
NUM_SPECIAL = 4


@dataclasses.dataclass
class SyntheticMLMConfig:
    vocab_size: int = 1000
    seq_len: int = 128
    mask_prob: float = 0.15
    noise: float = 0.05  # chance a chain step jumps uniformly
    seed: int = 0


class SyntheticMLM:
    """Generates BERT pretraining batches: ids/mask/types/mlm targets/nsp."""

    def __init__(self, cfg: SyntheticMLMConfig):
        assert cfg.vocab_size > NUM_SPECIAL + 1
        self.cfg = cfg
        rng = np.random.default_rng(cfg.seed)
        n_content = cfg.vocab_size - NUM_SPECIAL
        self._perm = rng.permutation(n_content)

    def _chain(self, rng, length: int) -> np.ndarray:
        n = self.cfg.vocab_size - NUM_SPECIAL
        out = np.empty(length, np.int64)
        tok = rng.integers(0, n)
        for i in range(length):
            out[i] = tok
            if rng.random() < self.cfg.noise:
                tok = rng.integers(0, n)
            else:
                tok = self._perm[tok]
        return out + NUM_SPECIAL

    def batch(self, batch_size: int, *, seed: int) -> dict[str, np.ndarray]:
        cfg = self.cfg
        rng = np.random.default_rng((cfg.seed, seed))
        L = cfg.seq_len
        # [CLS] a... [SEP] b... [SEP] — split content evenly.
        n_a = (L - 3) // 2
        n_b = L - 3 - n_a
        ids = np.zeros((batch_size, L), np.int32)
        types = np.zeros((batch_size, L), np.int32)
        nsp = np.zeros((batch_size,), np.int32)
        for i in range(batch_size):
            a = self._chain(rng, n_a + n_b)
            if rng.random() < 0.5:
                b = a[n_a:]
                nsp[i] = 0
            else:
                b = self._chain(rng, n_b)
                nsp[i] = 1
            row = np.concatenate([[CLS], a[:n_a], [SEP], b[:n_b], [SEP]])
            ids[i] = row
            types[i, n_a + 2 :] = 1
        attention_mask = np.ones((batch_size, L), bool)

        # BERT masking on content positions only.
        content = ids >= NUM_SPECIAL
        r = rng.random(ids.shape)
        selected = content & (r < cfg.mask_prob)
        targets = np.where(selected, ids, -1).astype(np.int32)
        action = rng.random(ids.shape)
        masked_ids = ids.copy()
        masked_ids[selected & (action < 0.8)] = MASK
        rand_sites = selected & (action >= 0.8) & (action < 0.9)
        masked_ids[rand_sites] = rng.integers(
            NUM_SPECIAL, cfg.vocab_size, size=int(rand_sites.sum())
        )
        return {
            "input_ids": masked_ids,
            "attention_mask": attention_mask,
            "token_type_ids": types,
            "mlm_targets": targets,
            "nsp_label": nsp,
        }


def bert_batch_specs(mesh, *, seq_sharded: bool = False) -> dict:
    """Per-leaf PartitionSpecs for a BERT batch (pass as train-step batch_spec).

    [B, L] leaves shard batch over the DP axes and (optionally) sequence over
    ``"seq"``; the [B] nsp label only shards the batch dim.
    """
    from jax.sharding import PartitionSpec as P

    from distributed_tensorflow_tpu.parallel.mesh import data_axes

    dp = data_axes(mesh)
    dp_spec = dp if dp else None
    seq = "seq" if (seq_sharded and "seq" in mesh.axis_names) else None
    spec_2d = P(dp_spec, seq)
    spec_1d = P(dp_spec)
    return {
        "input_ids": spec_2d,
        "attention_mask": spec_2d,
        "token_type_ids": spec_2d,
        "mlm_targets": spec_2d,
        "nsp_label": spec_1d,
    }


def mlm_device_batches(
    dataset: SyntheticMLM,
    mesh,
    global_batch: int,
    *,
    seq_sharded: bool = False,
    seed: int = 0,
):
    """Infinite iterator of placed BERT batches.

    ``seq_sharded=True`` additionally shards the [B, L] leaves' second dim
    over the mesh's ``"seq"`` axis (for ring-attention runs).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu.parallel.mesh import data_axes

    dp = data_axes(mesh)
    dp_spec = dp if dp else None
    seq = "seq" if (seq_sharded and "seq" in mesh.axis_names) else None
    spec_2d = NamedSharding(mesh, P(dp_spec, seq))
    spec_1d = NamedSharding(mesh, P(dp_spec))
    n_proc = jax.process_count()
    proc = jax.process_index()
    if global_batch % n_proc:
        raise ValueError(f"global batch {global_batch} not divisible by {n_proc} hosts")
    step = 0
    while True:
        full = dataset.batch(global_batch, seed=step)
        local_b = global_batch // n_proc
        local = {
            k: v[proc * local_b : (proc + 1) * local_b] for k, v in full.items()
        }
        yield {
            k: jax.make_array_from_process_local_data(
                spec_1d if v.ndim == 1 else spec_2d, v
            )
            for k, v in local.items()
        }
        step += 1
