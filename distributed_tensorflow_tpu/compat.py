"""jax version compatibility shims (imported for side effects).

The codebase targets the current jax API surface; on older runtimes
(0.4.x) two symbols it uses everywhere are missing, so the package
installs drop-in aliases at import time rather than scattering
version branches through every call site:

- ``jax.shard_map`` — lived at ``jax.experimental.shard_map.shard_map``
  with ``check_rep`` instead of ``check_vma``.
- ``jax.lax.axis_size`` — ``jax.core.axis_frame(name)`` returns the same
  static int inside a binding shard_map/pmap, and raises the same
  ``NameError`` on unbound names (models/bert.py ``_axis_bound`` relies
  on that).

Both installs are guarded: on a jax that already exports the symbol this
module is a no-op, and the shims can be deleted once the floor runtime
moves past 0.4.x.
"""

from __future__ import annotations

import math

import jax


def _install() -> None:
    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
            return _shard_map(
                f,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                check_rep=check_vma,
            )

        jax.shard_map = shard_map

    if not hasattr(jax.lax, "axis_size"):

        def axis_size(axis_name):
            if isinstance(axis_name, (tuple, list)):
                return math.prod(jax.core.axis_frame(a) for a in axis_name)
            return jax.core.axis_frame(axis_name)

        jax.lax.axis_size = axis_size


_install()
