// Native host-side input pipeline: threaded shuffle/augment/prefetch.
//
// The TPU-native runtime analog of the input-pipeline layer the reference
// gets from the TF C++ runtime (SURVEY.md §2 "Input pipelines" row; the repo
// itself is Python, its native speed comes from tf.data's C++ threadpool).
// Here the same capability is built directly: worker threads draw epoch
// permutations, apply augmentation (pad-crop + horizontal flip + optional
// per-image standardization), and stage finished batches in a bounded ring
// so the Python step loop never blocks on augmentation — it only memcpy's
// the next staged batch and hands it to jax.
//
// C ABI (ctypes-friendly), no external dependencies, C++17 + pthreads.

#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <queue>
#include <random>
#include <thread>
#include <vector>

namespace {

struct Batch {
  std::vector<float> images;
  std::vector<int32_t> labels;
};

struct Config {
  const float* images;    // [n, h, w, c] contiguous
  const int32_t* labels;  // [n]
  int64_t n;
  int h, w, c;
  int batch;
  int pad;              // pad-crop margin (0 = off)
  int flip;             // 1 = random horizontal flip
  int standardize;      // 1 = per-image mean/std normalization
  uint64_t seed;
};

class Pipeline {
 public:
  Pipeline(const Config& cfg, int n_threads, int queue_cap)
      : cfg_(cfg), cap_(queue_cap), stop_(false), next_ticket_(0), next_out_(0) {
    if (n_threads < 1) n_threads = 1;
    for (int t = 0; t < n_threads; ++t) {
      workers_.emplace_back([this, t] { Work(t); });
    }
  }

  ~Pipeline() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_space_.notify_all();
    cv_data_.notify_all();
    for (auto& th : workers_) th.join();
  }

  // Blocks until the next in-order batch is staged, then copies it out.
  void Next(float* out_images, int32_t* out_labels) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_data_.wait(lk, [this] { return stop_ || !ready_.empty(); });
    if (stop_) return;
    Batch b = std::move(ready_.front());
    ready_.pop();
    lk.unlock();
    // notify_all: only the worker holding ticket == next_out_ can proceed;
    // notify_one could wake a different one, which re-sleeps, and the
    // eligible worker would wait forever — permanent stall.
    cv_space_.notify_all();
    std::memcpy(out_images, b.images.data(), b.images.size() * sizeof(float));
    std::memcpy(out_labels, b.labels.data(), b.labels.size() * sizeof(int32_t));
  }

 private:
  // Deterministic per-ticket RNG: batch k is identical regardless of thread
  // count or interleaving — reproducibility is part of the framework's
  // contract (the reference's async input raced; see SURVEY.md §4).
  void Work(int /*tid*/) {
    const int64_t img_elems = int64_t(cfg_.h) * cfg_.w * cfg_.c;
    while (true) {
      const uint64_t ticket = next_ticket_.fetch_add(1);
      Batch b;
      b.images.resize(size_t(cfg_.batch) * img_elems);
      b.labels.resize(cfg_.batch);
      std::mt19937_64 rng(cfg_.seed * 0x9E3779B97F4A7C15ULL + ticket);
      for (int i = 0; i < cfg_.batch; ++i) {
        const int64_t idx =
            std::uniform_int_distribution<int64_t>(0, cfg_.n - 1)(rng);
        const float* src = cfg_.images + idx * img_elems;
        float* dst = b.images.data() + int64_t(i) * img_elems;
        Augment(src, dst, rng);
        b.labels[i] = cfg_.labels[idx];
      }
      // Stage in ticket order so output order is deterministic.
      std::unique_lock<std::mutex> lk(mu_);
      cv_space_.wait(lk, [this, ticket] {
        return stop_ ||
               (ticket == next_out_ && ready_.size() < size_t(cap_));
      });
      if (stop_) return;
      ready_.push(std::move(b));
      ++next_out_;
      lk.unlock();
      cv_data_.notify_one();
      cv_space_.notify_all();
    }
  }

  void Augment(const float* src, float* dst, std::mt19937_64& rng) {
    const int h = cfg_.h, w = cfg_.w, c = cfg_.c;
    int dy = 0, dx = 0;
    bool flip = false;
    if (cfg_.pad > 0) {
      dy = std::uniform_int_distribution<int>(-cfg_.pad, cfg_.pad)(rng);
      dx = std::uniform_int_distribution<int>(-cfg_.pad, cfg_.pad)(rng);
    }
    if (cfg_.flip) flip = std::uniform_int_distribution<int>(0, 1)(rng) != 0;
    for (int y = 0; y < h; ++y) {
      const int sy = y + dy;
      for (int x = 0; x < w; ++x) {
        int sx = flip ? (w - 1 - x) + dx : x + dx;
        float* d = dst + (int64_t(y) * w + x) * c;
        if (sy < 0 || sy >= h || sx < 0 || sx >= w) {
          std::memset(d, 0, sizeof(float) * c);
        } else {
          std::memcpy(d, src + (int64_t(sy) * w + sx) * c, sizeof(float) * c);
        }
      }
    }
    if (cfg_.standardize) {
      const int64_t n = int64_t(h) * w * c;
      double sum = 0, sq = 0;
      for (int64_t i = 0; i < n; ++i) sum += dst[i];
      const double mean = sum / n;
      for (int64_t i = 0; i < n; ++i) {
        const double v = dst[i] - mean;
        sq += v * v;
      }
      // tf.image.per_image_standardization's adjusted stddev floor.
      const double stddev = std::max(std::sqrt(sq / n), 1.0 / std::sqrt((double)n));
      for (int64_t i = 0; i < n; ++i) {
        dst[i] = float((dst[i] - mean) / stddev);
      }
    }
  }

  Config cfg_;
  int cap_;
  bool stop_;
  std::atomic<uint64_t> next_ticket_;
  uint64_t next_out_;
  std::vector<std::thread> workers_;
  std::queue<Batch> ready_;
  std::mutex mu_;
  std::condition_variable cv_data_, cv_space_;
};

}  // namespace

extern "C" {

void* dp_create(const float* images, const int32_t* labels, int64_t n, int h,
                int w, int c, int batch, int pad, int flip, int standardize,
                uint64_t seed, int n_threads, int queue_cap) {
  Config cfg{images, labels, n, h, w, c, batch, pad, flip, standardize, seed};
  return new Pipeline(cfg, n_threads, queue_cap);
}

void dp_next(void* handle, float* out_images, int32_t* out_labels) {
  static_cast<Pipeline*>(handle)->Next(out_images, out_labels);
}

void dp_destroy(void* handle) { delete static_cast<Pipeline*>(handle); }

}  // extern "C"
