// Native host-side input pipeline: threaded shuffle/augment/prefetch.
//
// The TPU-native runtime analog of the input-pipeline layer the reference
// gets from the TF C++ runtime (SURVEY.md §2 "Input pipelines" row; the repo
// itself is Python, its native speed comes from tf.data's C++ threadpool).
// Here the same capability is built directly:
//
// - Sampling is per-epoch permutation WITHOUT replacement: stream position p
//   maps to example perm_e(p mod E) where perm_e is a Feistel-network
//   permutation of [0, n) keyed by (seed, epoch e) — O(1) per draw, no
//   shared permutation array, so any worker can compute any batch
//   independently and batch k is identical regardless of thread count.
// - Augmentation: pad-crop + horizontal flip + per-image standardization
//   (CIFAR-style), or random-resized-crop to a target size with bilinear
//   resampling + per-channel mean/std normalization (ImageNet-style).
//   Sources may be f32 or u8 (u8 enables memory-mapped ImageNet caches).
// - Finished batches stage in a bounded ring in ticket order, so the Python
//   step loop never blocks on augmentation — it only memcpy's the next
//   staged batch and hands it to jax. `start_ticket` lets a restored run
//   resume the stream at batch N instead of replaying 0..N-1.
//
// C ABI (ctypes-friendly), no external dependencies, C++17 + pthreads.

#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <queue>
#include <random>
#include <thread>
#include <vector>

namespace {

inline uint64_t SplitMix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

// Format-preserving permutation of [0, n) via a 4-round balanced Feistel
// network over the smallest even-bit-width domain covering n, with
// cycle-walking to stay inside [0, n). Each (seed, epoch) keys a distinct
// permutation; evaluation is O(1) per index (expected <2 walk steps), so
// workers need no shared shuffle state — the property that makes batch
// content independent of thread scheduling.
class EpochPerm {
 public:
  EpochPerm(uint64_t n, uint64_t seed, uint64_t epoch) : n_(n) {
    int bits = 1;
    while ((1ULL << bits) < n_) ++bits;
    half_bits_ = (bits + 1) / 2;
    half_mask_ = (1ULL << half_bits_) - 1;
    const uint64_t base = SplitMix64(seed ^ (epoch * 0xD1B54A32D192ED03ULL));
    for (int r = 0; r < kRounds; ++r) keys_[r] = SplitMix64(base + r);
  }

  uint64_t operator()(uint64_t x) const {
    do {
      uint64_t l = x >> half_bits_, r = x & half_mask_;
      for (int i = 0; i < kRounds; ++i) {
        const uint64_t f = SplitMix64(r ^ keys_[i]) & half_mask_;
        const uint64_t nl = r;
        r = l ^ f;
        l = nl;
      }
      x = (l << half_bits_) | r;
    } while (x >= n_);  // cycle-walk: revisits stay a bijection on [0, n)
    return x;
  }

 private:
  static constexpr int kRounds = 4;
  uint64_t n_, half_mask_, keys_[4];
  int half_bits_;
};

struct Batch {
  std::vector<float> images;
  std::vector<int32_t> labels;
};

struct Config {
  const void* images;     // [n, h, w, c] contiguous, f32 or u8
  const int32_t* labels;  // [n]
  int64_t n;
  int h, w, c;            // source geometry
  int out_h, out_w;       // output geometry (== h, w unless cropping/resizing)
  int batch;              // examples per emitted batch (this host's share)
  int pad;                // pad-crop margin (0 = off; CIFAR-style)
  int flip;               // 1 = random horizontal flip
  int standardize;        // 1 = per-image mean/std normalization
  int rrc;                // 1 = random-resized-crop to (out_h, out_w)
  float rrc_min_area;     // min crop area fraction for rrc (e.g. 0.08)
  int src_u8;             // 1 = source pixels are u8 (scaled by 1/255)
  const float* mean;      // per-channel mean ([c]) or null
  const float* stddev;    // per-channel std  ([c]) or null
  uint64_t seed;
  // Multi-host epoch layout: stream position of example i of ticket t is
  //   offset + (t % batches_per_epoch) * stride + i,  epoch = t / bpe
  // where bpe = epoch_examples / stride. Single host: offset 0, stride ==
  // batch. Host k of m: offset = k * batch, stride = m * batch — all hosts
  // share one permutation and read disjoint slices, the explicit form of
  // tf.data's shard(num_hosts, host_id) idiom.
  uint64_t stream_offset;
  uint64_t stream_stride;
};

class Pipeline {
 public:
  Pipeline(const Config& cfg, int n_threads, int queue_cap, uint64_t start_ticket)
      : cfg_(cfg),
        cap_(queue_cap),
        stop_(false),
        next_ticket_(start_ticket),
        next_out_(start_ticket) {
    if (cfg_.stream_stride == 0) cfg_.stream_stride = cfg_.batch;
    // Per-epoch examples: whole strides only (drop-tail, like the numpy
    // loader) so every epoch is the same static batch count.
    batches_per_epoch_ = cfg_.n / cfg_.stream_stride;
    if (batches_per_epoch_ == 0) batches_per_epoch_ = 1;  // n < stride: wrap
    if (n_threads < 1) n_threads = 1;
    for (int t = 0; t < n_threads; ++t) {
      workers_.emplace_back([this] { Work(); });
    }
  }

  ~Pipeline() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_space_.notify_all();
    cv_data_.notify_all();
    for (auto& th : workers_) th.join();
  }

  // Blocks until the next in-order batch is staged, then copies it out.
  // Returns 1 on success, 0 if the pipeline was stopped (outputs untouched —
  // the caller must not read them).
  int Next(float* out_images, int32_t* out_labels) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_data_.wait(lk, [this] { return stop_ || !ready_.empty(); });
    if (stop_ && ready_.empty()) return 0;
    Batch b = std::move(ready_.front());
    ready_.pop();
    lk.unlock();
    // notify_all: only the worker holding ticket == next_out_ can proceed;
    // notify_one could wake a different one, which re-sleeps, and the
    // eligible worker would wait forever — permanent stall.
    cv_space_.notify_all();
    std::memcpy(out_images, b.images.data(), b.images.size() * sizeof(float));
    std::memcpy(out_labels, b.labels.data(), b.labels.size() * sizeof(int32_t));
    return 1;
  }

 private:
  // Deterministic per-ticket RNG: batch k is identical regardless of thread
  // count or interleaving — reproducibility is part of the framework's
  // contract (the reference's async input raced; see SURVEY.md §4).
  void Work() {
    const int64_t out_elems = int64_t(cfg_.out_h) * cfg_.out_w * cfg_.c;
    while (true) {
      const uint64_t ticket = next_ticket_.fetch_add(1);
      Batch b;
      b.images.resize(size_t(cfg_.batch) * out_elems);
      b.labels.resize(cfg_.batch);
      std::mt19937_64 rng(SplitMix64(cfg_.seed ^ (ticket * 0x9E3779B97F4A7C15ULL)));
      const uint64_t epoch = ticket / batches_per_epoch_;
      const uint64_t slot = ticket % batches_per_epoch_;
      const EpochPerm perm(cfg_.n, cfg_.seed, epoch);
      for (int i = 0; i < cfg_.batch; ++i) {
        const uint64_t pos =
            (cfg_.stream_offset + slot * cfg_.stream_stride + i) % cfg_.n;
        const int64_t idx = int64_t(perm(pos));
        float* dst = b.images.data() + int64_t(i) * out_elems;
        Augment(idx, dst, rng);
        b.labels[i] = cfg_.labels[idx];
      }
      // Stage in ticket order so output order is deterministic.
      std::unique_lock<std::mutex> lk(mu_);
      cv_space_.wait(lk, [this, ticket] {
        return stop_ ||
               (ticket == next_out_ && ready_.size() < size_t(cap_));
      });
      if (stop_) return;
      ready_.push(std::move(b));
      ++next_out_;
      lk.unlock();
      cv_data_.notify_one();
      cv_space_.notify_all();
    }
  }

  inline float SrcPx(int64_t img, int y, int x, int ch) const {
    const int64_t off =
        ((img * cfg_.h + y) * int64_t(cfg_.w) + x) * cfg_.c + ch;
    if (cfg_.src_u8) {
      return static_cast<const uint8_t*>(cfg_.images)[off] * (1.0f / 255.0f);
    }
    return static_cast<const float*>(cfg_.images)[off];
  }

  void Augment(int64_t idx, float* dst, std::mt19937_64& rng) {
    if (cfg_.rrc || cfg_.out_h != cfg_.h || cfg_.out_w != cfg_.w) {
      CropResize(idx, dst, rng);
    } else {
      PadCrop(idx, dst, rng);
    }
    const int64_t n = int64_t(cfg_.out_h) * cfg_.out_w * cfg_.c;
    if (cfg_.mean && cfg_.stddev) {
      for (int64_t i = 0; i < n; ++i) {
        const int ch = i % cfg_.c;
        dst[i] = (dst[i] - cfg_.mean[ch]) / cfg_.stddev[ch];
      }
    }
    if (cfg_.standardize) {
      double sum = 0, sq = 0;
      for (int64_t i = 0; i < n; ++i) sum += dst[i];
      const double mean = sum / n;
      for (int64_t i = 0; i < n; ++i) {
        const double v = dst[i] - mean;
        sq += v * v;
      }
      // tf.image.per_image_standardization's adjusted stddev floor.
      const double stddev = std::max(std::sqrt(sq / n), 1.0 / std::sqrt((double)n));
      for (int64_t i = 0; i < n; ++i) {
        dst[i] = float((dst[i] - mean) / stddev);
      }
    }
  }

  // CIFAR-style: reflect nothing, zero-pad margin, random crop + flip.
  void PadCrop(int64_t idx, float* dst, std::mt19937_64& rng) {
    const int h = cfg_.h, w = cfg_.w, c = cfg_.c;
    int dy = 0, dx = 0;
    bool flip = false;
    if (cfg_.pad > 0) {
      dy = std::uniform_int_distribution<int>(-cfg_.pad, cfg_.pad)(rng);
      dx = std::uniform_int_distribution<int>(-cfg_.pad, cfg_.pad)(rng);
    }
    if (cfg_.flip) flip = std::uniform_int_distribution<int>(0, 1)(rng) != 0;
    for (int y = 0; y < h; ++y) {
      const int sy = y + dy;
      for (int x = 0; x < w; ++x) {
        int sx = flip ? (w - 1 - x) + dx : x + dx;
        float* d = dst + (int64_t(y) * w + x) * c;
        if (sy < 0 || sy >= h || sx < 0 || sx >= w) {
          std::memset(d, 0, sizeof(float) * c);
        } else {
          for (int ch = 0; ch < c; ++ch) d[ch] = SrcPx(idx, sy, sx, ch);
        }
      }
    }
  }

  // ImageNet-style: random-resized-crop (scale in [min_area, 1], aspect in
  // [3/4, 4/3], 10 attempts then center fallback — the standard Inception
  // crop) or, when rrc == 0, a center crop; bilinear resample to
  // (out_h, out_w); optional flip folded into the sampling.
  void CropResize(int64_t idx, float* dst, std::mt19937_64& rng) {
    const int h = cfg_.h, w = cfg_.w, c = cfg_.c;
    const int oh = cfg_.out_h, ow = cfg_.out_w;
    int cy = 0, cx = 0, ch_ = h, cw_ = w;
    if (cfg_.rrc) {
      std::uniform_real_distribution<float> u01(0.0f, 1.0f);
      bool found = false;
      for (int attempt = 0; attempt < 10 && !found; ++attempt) {
        const float area = float(h) * w;
        const float target =
            area * (cfg_.rrc_min_area +
                    u01(rng) * (1.0f - cfg_.rrc_min_area));
        const float log_r = std::log(3.0f / 4.0f) +
                            u01(rng) * (std::log(4.0f / 3.0f) - std::log(3.0f / 4.0f));
        const float ratio = std::exp(log_r);
        const int tw = int(std::lround(std::sqrt(target * ratio)));
        const int th = int(std::lround(std::sqrt(target / ratio)));
        if (tw > 0 && th > 0 && tw <= w && th <= h) {
          cw_ = tw;
          ch_ = th;
          cy = std::uniform_int_distribution<int>(0, h - th)(rng);
          cx = std::uniform_int_distribution<int>(0, w - tw)(rng);
          found = true;
        }
      }
      if (!found) {  // center fallback
        ch_ = cw_ = std::min(h, w);
        cy = (h - ch_) / 2;
        cx = (w - cw_) / 2;
      }
    } else {
      ch_ = cw_ = std::min(h, w);
      cy = (h - ch_) / 2;
      cx = (w - cw_) / 2;
    }
    bool flip = false;
    if (cfg_.flip) flip = std::uniform_int_distribution<int>(0, 1)(rng) != 0;
    // Bilinear resample crop box -> (oh, ow), align_corners=false convention.
    // Source coordinates clamp to the CROP WINDOW, not the full image: the
    // crop is resized in isolation (torchvision/TF RRC convention), so border
    // output pixels never blend content from outside the sampled box. The
    // clamp also happens BEFORE floor/frac: an unclamped floor at fy < cy
    // (upscale at the box's top/left edge) would invert the blend weights.
    const float sy_scale = float(ch_) / oh, sx_scale = float(cw_) / ow;
    for (int y = 0; y < oh; ++y) {
      float fy = (y + 0.5f) * sy_scale - 0.5f + cy;
      fy = std::max(float(cy), std::min(float(cy + ch_ - 1), fy));
      const int y0 = int(fy);
      const int y1 = std::min(cy + ch_ - 1, y0 + 1);
      const float wy = fy - y0;
      for (int x = 0; x < ow; ++x) {
        const int xo = flip ? (ow - 1 - x) : x;
        float fx = (x + 0.5f) * sx_scale - 0.5f + cx;
        fx = std::max(float(cx), std::min(float(cx + cw_ - 1), fx));
        const int x0 = int(fx);
        const int x1 = std::min(cx + cw_ - 1, x0 + 1);
        const float wx = fx - x0;
        float* d = dst + (int64_t(y) * ow + xo) * c;
        for (int chn = 0; chn < c; ++chn) {
          const float p00 = SrcPx(idx, y0, x0, chn);
          const float p01 = SrcPx(idx, y0, x1, chn);
          const float p10 = SrcPx(idx, y1, x0, chn);
          const float p11 = SrcPx(idx, y1, x1, chn);
          d[chn] = p00 * (1 - wy) * (1 - wx) + p01 * (1 - wy) * wx +
                   p10 * wy * (1 - wx) + p11 * wy * wx;
        }
      }
    }
  }

  Config cfg_;
  int cap_;
  bool stop_;
  uint64_t batches_per_epoch_;
  std::atomic<uint64_t> next_ticket_;
  uint64_t next_out_;
  std::vector<std::thread> workers_;
  std::queue<Batch> ready_;
  std::mutex mu_;
  std::condition_variable cv_data_, cv_space_;
};

}  // namespace

extern "C" {

void* dp_create(const void* images, const int32_t* labels, int64_t n, int h,
                int w, int c, int out_h, int out_w, int batch, int pad,
                int flip, int standardize, int rrc, float rrc_min_area,
                int src_u8, const float* mean, const float* stddev,
                uint64_t seed, uint64_t stream_offset, uint64_t stream_stride,
                uint64_t start_ticket, int n_threads, int queue_cap) {
  Config cfg{images,  labels, n,
             h,       w,      c,
             out_h,   out_w,  batch,
             pad,     flip,   standardize,
             rrc,     rrc_min_area, src_u8,
             mean,    stddev, seed,
             stream_offset,   stream_stride};
  return new Pipeline(cfg, n_threads, queue_cap, start_ticket);
}

int dp_next(void* handle, float* out_images, int32_t* out_labels) {
  return static_cast<Pipeline*>(handle)->Next(out_images, out_labels);
}

void dp_destroy(void* handle) { delete static_cast<Pipeline*>(handle); }

}  // extern "C"
