"""Headline benchmark — ONE JSON line for the driver protocol.

Default workload (r5, VERDICT r4 Weak #3): BERT-base pretraining at L=512
— the transformer config is the axis where the measured chip ceiling is
actually approachable (docs/PERF.md r5: MFU 0.360 -> 0.600 this round,
recipe campaign + layout-native packed flash kernels), where the conv
workloads sit at a measured structural ~0.17 plateau (docs/PERF.md r3/r4
CASE CLOSED). ``BENCH_WORKLOAD=resnet50`` selects the unchanged ResNet-50
line (rounds 1-4's default); ``BENCH_WORKLOAD=bert`` still works and
equals the default.

Prints ONE JSON line: ``{"metric", "value", "unit", "vs_baseline"}``.
``vs_baseline`` is measured MFU / 0.55 — the reference repo publishes no
numbers (BASELINE.json "published": {}, SURVEY.md §6), so the ≥55% MFU
target from BASELINE.json:5 is the baseline bar.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

# ResNet-50 at 224x224: ~4.09 GFLOP forward per image (the standard count);
# fwd+bwd ~= 3x forward.
FLOPS_PER_IMAGE = 3 * 4.09e9

# Known per-chip peak bf16 FLOP/s for MFU accounting; fall back to v5e.
PEAK_FLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def chip_peak_flops(device) -> tuple[float, bool]:
    """Return (per-chip peak bf16 FLOP/s, whether it was a known match)."""
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in PEAK_FLOPS.items():
        if key in kind:
            return peak, True
    return 197e12, False


def main():
    workload = os.environ.get("BENCH_WORKLOAD", "bert")
    if workload not in ("bert", "resnet50"):
        raise SystemExit(f"BENCH_WORKLOAD must be 'bert' or 'resnet50', got {workload!r}")
    if workload == "bert":
        # Transformer workload (BASELINE.json:11) — the r5 default.
        sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "scripts"))
        import bench_bert

        bench_bert.driver_line()
        return
    from distributed_tensorflow_tpu.data import synthetic_image_classification
    from distributed_tensorflow_tpu.models import ResNet50
    from distributed_tensorflow_tpu.parallel import collectives as coll
    from distributed_tensorflow_tpu.parallel.mesh import build_mesh
    from distributed_tensorflow_tpu.train import create_train_state, make_train_step
    from distributed_tensorflow_tpu.train.objectives import (
        init_model,
        make_classification_loss,
    )
    from distributed_tensorflow_tpu.train.step import place_state

    devices = jax.devices()
    n = len(devices)
    on_tpu = devices[0].platform == "tpu"
    # b=128/chip won the r2 batch sweep (scripts/mfu_sweep.py: 0.136 @ 64,
    # 0.158 @ 128, 0.156 @ 256, 0.147 @ 512 on v5e).
    per_chip_batch = int(os.environ.get("BENCH_BATCH", 128 if on_tpu else 8))
    image_hw = 224 if on_tpu else 64
    global_batch = per_chip_batch * n

    mesh = build_mesh({"data": -1})
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    params, model_state = init_model(
        model, jax.random.key(0), jnp.zeros((1, image_hw, image_hw, 3), jnp.float32)
    )
    tx = optax.sgd(0.1, momentum=0.9)
    state = place_state(create_train_state(params, tx, model_state), mesh)
    step = make_train_step(make_classification_loss(model), tx, mesh)

    ds = synthetic_image_classification(
        global_batch, (image_hw, image_hw, 3), 1000, seed=0
    )
    rng = jax.random.key(0)

    # BENCH_FEED=stream: feed every step a fresh host-assembled batch
    # through the async prefetch stage (data/prefetch.py) instead of one
    # resident device batch — measures end-to-end throughput WITH the feed
    # in the loop (vs the default device-only number). BENCH_PREFETCH sets
    # the lookahead depth (0 = synchronous feed, the r5-era behavior).
    # BENCH_INPUT_DTYPE=bfloat16 narrows the assembled image batch at
    # copy-out (data/loader.py out_dtype), halving host->device image
    # bytes — the feed-side lever for the r19 input-path study.
    feed_mode = os.environ.get("BENCH_FEED", "")
    input_dtype = os.environ.get("BENCH_INPUT_DTYPE", "float32")
    if feed_mode == "stream":
        from distributed_tensorflow_tpu.data import device_batches
        from distributed_tensorflow_tpu.data.prefetch import prefetch

        depth = int(os.environ.get("BENCH_PREFETCH", "2"))
        stream = prefetch(
            device_batches(ds, mesh, global_batch, seed=0, out_dtype=input_dtype),
            depth,
        )
    elif feed_mode:
        raise SystemExit(f"BENCH_FEED must be '' or 'stream', got {feed_mode!r}")
    else:
        stream = None
        batch = coll.shard_batch({"image": ds.images, "label": ds.labels}, mesh)

    # Warmup: compile + 2 steady steps. Synchronization note: on the tunneled
    # TPU platform here, block_until_ready returns before the computation
    # drains, so every timed region ends with a value fetch of a metric that
    # data-depends on the whole donated-state chain — that is a true barrier.
    def window(n_steps):
        nonlocal state
        t0 = time.perf_counter()
        for _ in range(n_steps):
            state, metrics = step(state, batch if stream is None else next(stream), rng)
        float(metrics["loss"])
        return time.perf_counter() - t0

    window(3)

    # Measurement discipline (VERDICT r2 Weak #2 + scripts/roofline.py):
    # the scalar fetch ending a window costs a ~130 ms tunnel round-trip,
    # so a single 20-step window overstates step time by ~6.5 ms (r2 did
    # exactly that). Run >=3 long windows plus short ones; the median
    # difference cancels the round-trip, and the spread is reported.
    n_long, n_short = (60, 1) if on_tpu else (3, 1)
    reps = 3
    longs = sorted(window(n_long) for _ in range(reps))
    shorts = sorted(window(n_short) for _ in range(reps))
    per_step = (longs[reps // 2] - shorts[reps // 2]) / (n_long - n_short)
    spread = (longs[-1] - longs[0]) / longs[reps // 2]
    if stream is not None:
        stream.close()

    images_per_sec_chip = global_batch / per_step / n
    # MFU accounting is defined for the 224x224 workload; scale FLOPs if the
    # CPU-smoke path shrank the image (conv FLOPs ~ HW^2).
    flops_per_image = FLOPS_PER_IMAGE * (image_hw / 224) ** 2
    peak, known = chip_peak_flops(devices[0])
    mfu = images_per_sec_chip * flops_per_image / peak
    peak_note = f"peak={peak / 1e12:.0f}T" + ("" if known else " ASSUMED")
    # Ceiling context (docs/PERF.md r3 "measured roofline"): this model's
    # arithmetic intensity (~90 flops/byte at ideal traffic) x the chip's
    # measured ~650 GB/s HBM bandwidth caps MFU at ~0.30 on a v5e —
    # the 0.55 target presumes a bandwidth/FLOP ratio this chip lacks.
    # The r4 kernel campaign (docs/PERF.md "CASE CLOSED") measured seven
    # custom-kernel configurations, all losing to XLA's in-context codegen:
    # ~0.17 is the practical max for this conv+BN model on this chip. The
    # same engine reaches 0.60 MFU on matmul-dominated BERT at L=512
    # (bench_bert.py, r5 packed-flash config) — which is why the driver
    # default workload is the transformer since r5.
    ceil_note = (
        "meas-roofline-ceiling~0.30, practical-max~0.17 per docs/PERF.md r4 "
        "kernel study; driver default is the transformer workload since r5"
        if on_tpu
        else "cpu-smoke"
    )
    print(
        json.dumps(
            {
                "metric": "resnet50_train_images_per_sec_per_chip",
                "value": round(images_per_sec_chip, 2),
                "unit": f"images/sec/chip (bf16, b={per_chip_batch}/chip, "
                f"{image_hw}x{image_hw}, {n}x {devices[0].device_kind}, "
                f"mfu={mfu:.3f}, median of {reps}x{n_long}-step windows, "
                f"spread={spread:.1%}, "
                + (
                    f"feed=stream+prefetch{stream.depth} in={input_dtype}, "
                    if stream is not None
                    else "feed=resident, "
                )
                + f"{peak_note}, {ceil_note})",
                "vs_baseline": round(mfu / 0.55, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
